"""Collective microbenchmarks over any mesh — the allreduce-step-time tool.

The reference's secondary north-star metric is "DDP allreduce step time"
(BASELINE.json:2). On a single chip that collective is compiler-eliminated
(bench.py measures DP-step *overhead* instead); the moment a multi-chip
mesh exists — ICI slice or multi-host pod — this script measures the real
thing: per-collective latency and achieved algorithmic bandwidth for the
facade's all_reduce / all_gather / reduce_scatter / permute at gradient
sizes, over whichever mesh axis you give it.

Bus-bandwidth accounting follows the NCCL-tests convention so numbers are
comparable to the reference's GPU rigs:

    allreduce      moves 2(n-1)/n * bytes   per participant
    allgather      moves   (n-1)/n * bytes
    reduce_scatter moves   (n-1)/n * bytes
    permute        moves             bytes  (one hop on the ring)

On the virtual CPU mesh (XLA_FLAGS=--xla_force_host_platform_device_count=N)
the "collectives" are shared-memory copies — the run is a harness smoke,
not a measurement; the banner says which you got.

Run (any env; on the chip follow docs/CHIP_PROTOCOL.md — no kill timers):
    python scripts/collective_bench.py --sizes 4 32 128
    python scripts/collective_bench.py --axis dp --iters 50
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.runtime.distributed import ReduceOp
from pytorch_distributed_tpu.runtime.mesh import MeshSpec, mesh_axis_size


def _timed(fn, x, iters, warmup=3):
    y = fn(x)
    for _ in range(warmup):
        y = fn(y)
    float(jnp.sum(y[..., :1]))  # sync via scalar fetch (relay-safe)
    t0 = time.perf_counter()
    for _ in range(iters):
        y = fn(y)
    float(jnp.sum(y[..., :1]))
    return (time.perf_counter() - t0) / iters


def main(argv=None):
    from pytorch_distributed_tpu.utils.benchlock import (
        acquire_measurement_lock,
    )

    _lock = acquire_measurement_lock()  # noqa: F841 — held for life
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--sizes", type=float, nargs="+", default=[4.0, 32.0],
                   help="payload sizes in MB (f32 elements)")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--axis", default=None,
                   help="mesh axis to run over (default: the whole mesh)")
    p.add_argument("--dp", type=int, default=-1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--fsdp", type=int, default=1)
    args = p.parse_args(argv)

    ptd.enable_compilation_cache()
    if not ptd.is_initialized():
        # guarded: embedding callers (tests, notebooks) keep their mesh
        ptd.init_process_group(
            mesh_spec=MeshSpec(dp=args.dp, tp=args.tp, fsdp=args.fsdp)
        )
    plat = ptd.platform()
    # participant count follows the requested axis, not the whole mesh —
    # the leading dim of every facade collective input must match it
    parts = (
        mesh_axis_size(args.axis) if args.axis else ptd.get_world_size()
    )
    print(f"# platform={plat} participants={parts} "
          f"axis={args.axis or '<all>'} "
          f"({'REAL collectives' if plat == 'tpu' and parts > 1 else 'smoke only: single device or shared-memory mesh'})",
          flush=True)
    if parts == 1:
        print("# 1 participant: collectives are identity; nothing to measure")
        return

    kw = {"axis": args.axis} if args.axis else {}
    colls = {
        # facade semantics: leading dim = participants. Every fn is
        # shape-preserving so the timed loop can chain output -> input
        # (one compile, real data dependencies between iterations).
        "all_reduce": (
            lambda x: jnp.broadcast_to(
                ptd.all_reduce(x, op=ReduceOp.AVG, **kw), x.shape
            ),
            lambda n, b: 2 * (n - 1) / n * b,
        ),
        "reduce_scatter": (
            lambda x: jnp.broadcast_to(
                ptd.reduce_scatter(x, op=ReduceOp.SUM, **kw), x.shape
            ),
            lambda n, b: (n - 1) / n * b,
        ),
        "all_gather": (
            # [parts, per] in -> [parts, per] replicated out: each
            # participant contributes its row
            lambda x: ptd.all_gather(x, **kw),
            lambda n, b: (n - 1) / n * b,
        ),
        "permute": (
            lambda x: ptd.permute(
                x, [(i, (i + 1) % parts) for i in range(parts)], **kw
            ),
            lambda n, b: b,
        ),
    }
    for mb in args.sizes:
        n_elem = int(mb * 1e6 / 4)
        # per-participant rows sized divisibly by parts so reduce_scatter's
        # tiled scatter dimension splits evenly
        per = max(n_elem // parts // parts, 1) * parts
        x = jnp.ones((parts, per), jnp.float32)
        payload = per * parts * 4
        for name, (fn, moved) in colls.items():
            try:
                dt = _timed(fn, x, args.iters)
                bw = moved(parts, payload) / dt / 1e9
                print(
                    f"{name:15s} {payload / 1e6:8.1f}MB "
                    f"{dt * 1e3:8.3f}ms  {bw:7.2f} GB/s busbw",
                    flush=True,
                )
            except Exception as e:  # keep later collectives running
                print(f"{name:15s} {payload / 1e6:8.1f}MB FAILED: "
                      f"{type(e).__name__}: {e}", flush=True)


if __name__ == "__main__":
    main()
