"""Collective microbenchmarks over any mesh — the allreduce-step-time tool.

The reference's secondary north-star metric is "DDP allreduce step time"
(BASELINE.json:2). On a single chip that collective is compiler-eliminated
(bench.py measures DP-step *overhead* instead); the moment a multi-chip
mesh exists — ICI slice or multi-host pod — this script measures the real
thing: per-collective latency and achieved algorithmic bandwidth for the
facade's all_reduce / all_gather / reduce_scatter / permute at gradient
sizes, over whichever mesh axis you give it.

Bus-bandwidth accounting follows the NCCL-tests convention so numbers are
comparable to the reference's GPU rigs:

    allreduce      moves 2(n-1)/n * bytes   per participant
    allgather      moves   (n-1)/n * bytes
    reduce_scatter moves   (n-1)/n * bytes
    permute        moves             bytes  (one hop on the ring)

On the virtual CPU mesh (XLA_FLAGS=--xla_force_host_platform_device_count=N)
the "collectives" are shared-memory copies — the run is a harness smoke,
not a measurement; the banner says which you got.

``--metrics-path`` writes every (op, size, world) measurement through
the MetricsWriter JSONL protocol (``split="comm_bench"``,
``event="collective"``) so cost-model fits and bench history can
consume past runs instead of re-parsing stdout prose. ``--fit PATH``
calibrates the α–β comms cost model (runtime/costmodel.py) from this
run's sweep and writes the ``costmodel.json`` artifact the
auto-parallel planner (ROADMAP item 4) consumes; the fit summary
prints each op's α/β/R² and the worst predicted-vs-measured ratio over
the sweep (the "within 2x" self-check).

Run (any env; on the chip follow docs/CHIP_PROTOCOL.md — no kill timers):
    python scripts/collective_bench.py --sizes 4 32 128
    python scripts/collective_bench.py --axis dp --iters 50
    python scripts/collective_bench.py --sizes 1 4 16 64 \
        --metrics-path runs/comm.jsonl --fit runs/costmodel.json
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.runtime.distributed import ReduceOp
from pytorch_distributed_tpu.runtime.mesh import MeshSpec, mesh_axis_size


def _timed(fn, x, iters, warmup=3):
    y = fn(x)
    for _ in range(warmup):
        y = fn(y)
    float(jnp.sum(y[..., :1]))  # sync via scalar fetch (relay-safe)
    t0 = time.perf_counter()
    for _ in range(iters):
        y = fn(y)
    float(jnp.sum(y[..., :1]))
    return (time.perf_counter() - t0) / iters


def main(argv=None):
    from pytorch_distributed_tpu.utils.benchlock import (
        acquire_measurement_lock,
    )

    _lock = acquire_measurement_lock()  # noqa: F841 — held for life
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--sizes", type=float, nargs="+", default=[4.0, 32.0],
                   help="payload sizes in MB (f32 elements)")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--axis", default=None,
                   help="mesh axis to run over (default: the whole mesh)")
    p.add_argument("--dp", type=int, default=-1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--metrics-path", default=None,
                   help="append per-(op, size, world) records as "
                   "MetricsWriter JSONL (split=comm_bench)")
    p.add_argument("--fit", default=None, metavar="COSTMODEL_JSON",
                   help="fit the α–β comms cost model from this sweep "
                   "and write it here")
    args = p.parse_args(argv)

    ptd.enable_compilation_cache()
    if not ptd.is_initialized():
        # guarded: embedding callers (tests, notebooks) keep their mesh
        ptd.init_process_group(
            mesh_spec=MeshSpec(dp=args.dp, tp=args.tp, fsdp=args.fsdp)
        )
    plat = ptd.platform()
    # participant count follows the requested axis, not the whole mesh —
    # the leading dim of every facade collective input must match it
    parts = (
        mesh_axis_size(args.axis) if args.axis else ptd.get_world_size()
    )
    print(f"# platform={plat} participants={parts} "
          f"axis={args.axis or '<all>'} "
          f"({'REAL collectives' if plat == 'tpu' and parts > 1 else 'smoke only: single device or shared-memory mesh'})",
          flush=True)
    if parts == 1:
        print("# 1 participant: collectives are identity; nothing to measure")
        return
    # transport label for records/model: the facade's XLA collectives on
    # this platform, or the native shm ring under a one-proc-per-rank
    # launch — a model fitted on one must not silently price the other
    from pytorch_distributed_tpu.runtime.distributed import (
        multiprocess_ring,
    )

    transport = (
        "hostring" if multiprocess_ring() is not None else f"spmd:{plat}"
    )
    records = []

    kw = {"axis": args.axis} if args.axis else {}
    colls = {
        # facade semantics: leading dim = participants. Every fn is
        # shape-preserving so the timed loop can chain output -> input
        # (one compile, real data dependencies between iterations).
        "all_reduce": (
            lambda x: jnp.broadcast_to(
                ptd.all_reduce(x, op=ReduceOp.AVG, **kw), x.shape
            ),
            lambda n, b: 2 * (n - 1) / n * b,
        ),
        "reduce_scatter": (
            lambda x: jnp.broadcast_to(
                ptd.reduce_scatter(x, op=ReduceOp.SUM, **kw), x.shape
            ),
            lambda n, b: (n - 1) / n * b,
        ),
        "all_gather": (
            # [parts, per] in -> [parts, per] replicated out: each
            # participant contributes its row
            lambda x: ptd.all_gather(x, **kw),
            lambda n, b: (n - 1) / n * b,
        ),
        "permute": (
            lambda x: ptd.permute(
                x, [(i, (i + 1) % parts) for i in range(parts)], **kw
            ),
            lambda n, b: b,
        ),
    }
    for mb in args.sizes:
        n_elem = int(mb * 1e6 / 4)
        # per-participant rows sized divisibly by parts so reduce_scatter's
        # tiled scatter dimension splits evenly
        per = max(n_elem // parts // parts, 1) * parts
        x = jnp.ones((parts, per), jnp.float32)
        payload = per * parts * 4
        for name, (fn, moved) in colls.items():
            try:
                dt = _timed(fn, x, args.iters)
                bw = moved(parts, payload) / dt / 1e9
                print(
                    f"{name:15s} {payload / 1e6:8.1f}MB "
                    f"{dt * 1e3:8.3f}ms  {bw:7.2f} GB/s busbw",
                    flush=True,
                )
                records.append({
                    "op": name,
                    "payload_bytes": payload,
                    "wire_bytes": int(moved(parts, payload)),
                    "seconds": dt,
                    "gb_per_s": bw,
                    "world": parts,
                    "transport": transport,
                    "iters": args.iters,
                })
            except Exception as e:  # keep later collectives running
                print(f"{name:15s} {payload / 1e6:8.1f}MB FAILED: "
                      f"{type(e).__name__}: {e}", flush=True)

    if args.metrics_path:
        from pytorch_distributed_tpu.train.metrics import MetricsWriter

        with MetricsWriter(args.metrics_path) as w:
            for i, r in enumerate(records):
                w.write(i, {"event": "collective", **r},
                        split="comm_bench")
        print(f"# {len(records)} records -> {args.metrics_path}",
              flush=True)

    if args.fit:
        from pytorch_distributed_tpu.runtime import costmodel

        model = costmodel.fit(records, transport)
        if not model.fits:
            print("# --fit: no fittable measurements (all failed or "
                  "1 participant)", file=sys.stderr)
            return 1
        path = model.save(args.fit)
        worst = costmodel.validate(model, records)
        print(f"# cost model ({transport}) -> {path}", flush=True)
        for (op, world), f in sorted(model.fits.items()):
            print(
                f"# fit {op:15s} world={world} "
                f"alpha={f.alpha_s * 1e6:9.1f}us "
                f"beta={f.beta_s_per_byte * 1e9:8.4f}ns/B "
                f"({f.bandwidth_gb_s:6.2f} GB/s) r2={f.r2:.3f} "
                f"n={f.n_samples} worst_ratio={worst.get(op, 0.0):.2f}x",
                flush=True,
            )
        bad = {op: r for op, r in worst.items() if r > 2.0}
        if bad:
            print(f"# WARNING: predictions off by >2x on the calibration "
                  f"sweep itself: {bad} — more sizes or more iters",
                  file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
