"""First EXECUTED Llama-3-8B step: on-device int4 build + scan_dequant decode.

Recipe 5 (BASELINE.json:11, SURVEY.md §7 hard part c) is the one
blueprint row that has only ever been proven abstractly (AOT lowering,
v5p-64 fit, XLA-cost-analysis step projection — tests/test_llama8b.py,
BASELINE.md). This script turns it into an executed fact on the ONE
real chip: a full-architecture Llama-3-8B (128256 vocab, 32 scanned
layers, GQA 32/8, 14336 FFN) decoding real tokens through the
int4 + per-layer-scan-dequant serving path (ops/quant.py,
models/scan.py).

Why random weights are the honest play here: there is no egress to
fetch real checkpoints, and throughput/memory do not depend on weight
values. The weights are built DIRECTLY on device in the exact layout
``quantize_for_scan_dequant`` produces — never materializing a bf16/f32
8B tree anywhere (host RAM or HBM):

* scanned block kernels: per LAYER, generate one layer's f32 kernel on
  device, int4-quantize it there, free the float transient, stack the
  32 quantized slices. Groupwise int4 math is slice-invariant (scales
  reduce axis -2 per layer), so per-layer-quantize+stack is bitwise
  the layout the whole-tree quantizer emits on a stacked kernel — the
  tiny preset asserts exactly that against the real pipeline.
* everything else (embed, lm_head, norm scales) rests in bf16.

Memory budget on a 16 GB v5e: ~3.5 GB int4 payload + ~0.2 GB scales
+ ~2.1 GB bf16 embed+lm_head at rest; decode transiently reconstructs
ONE layer (~0.44 GB bf16 under Policy(param_dtype=bf16)) per scan tick.

Chip rules (docs/CHIP_PROTOCOL.md): no external kill timers; the script
budgets itself between phases/leaves via PTD_PROBE_BUDGET_S and exits
cleanly when over. The 8b preset refuses to run on CPU (a consumption
metric on the host would be noise wearing a TPU name); --preset tiny is
the CPU rehearsal path and is exercised by tests/test_llama8b.py.
"""

import argparse
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

t0 = time.time()
BUDGET_S = float(os.environ.get("PTD_PROBE_BUDGET_S", "2400"))


def log(msg):
    print(f"[{time.time() - t0:7.1f}s] {msg}", flush=True)


def over_budget():
    return time.time() - t0 > BUDGET_S


import jax
import jax.numpy as jnp
import numpy as np

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from pytorch_distributed_tpu.ops.quant import (
    quantize_tree_int4,
    quantized_bytes,
)
from pytorch_distributed_tpu.parallel.sharding import path_str
from pytorch_distributed_tpu.runtime.precision import Policy, use_policy

# mirror quantize_for_scan_dequant's gate: only kernels inside the
# scanned stack, judged on the STACKED leaf (that is what the real
# pipeline quantizes)
_INCLUDE = re.compile(r"/block/.*/kernel$")
_MIN_SIZE = 4096


def _quantizable(path: str, sds) -> bool:
    return (
        _INCLUDE.search("/" + path) is not None
        and sds.ndim >= 2
        and sds.size >= _MIN_SIZE
        and sds.shape[-1] % 2 == 0
    )


class BuildBudgetExceeded(RuntimeError):
    """Raised EARLY (after the first leaf's first two layers) when the
    measured per-compile/per-call times project the full build past the
    probe budget minus the decode-compile reserve — so the caller can
    shrink scope while the window is still mostly unspent (VERDICT r4
    weak #6: the chain's highest-value item must not die to budget math
    that was knowable upfront)."""

    def __init__(self, msg, t_compile, t_call, n_quant, layers):
        super().__init__(msg)
        self.t_compile = t_compile
        self.t_call = t_call
        self.n_quant = n_quant
        self.layers = layers


def build_int4_params(
    model, ids0, seed=0, log_fn=lambda m: None, decode_reserve_s=0.0
):
    """The model's params tree in quantize_for_scan_dequant's int4
    layout, built leaf-by-leaf ON DEVICE — peak float transient is one
    LAYER's largest kernel, never the whole tree.

    After the first quantizable leaf's first (compile) and second
    (steady) layer calls, the whole build's cost is projected and
    logged; if it lands past ``BUDGET_S - decode_reserve_s`` the build
    aborts with :class:`BuildBudgetExceeded` carrying the measured
    times, so the caller can retry at a depth the window affords.
    """
    shapes = jax.eval_shape(
        lambda k: model.init(k, ids0), jax.random.key(seed)
    )["params"]
    flat, treedef = jax.tree_util.tree_flatten_with_path(shapes)
    n_quant = sum(
        1 for path, sds in flat if _quantizable(path_str(path), sds)
    )
    key = jax.random.key(seed + 1)
    leaves = []
    quant_seen = 0
    for i, (path, sds) in enumerate(flat):
        p = path_str(path)
        key, sub = jax.random.split(key)
        if _quantizable(p, sds):
            quant_seen += 1
            first_quant = quant_seen == 1
            L, per = sds.shape[0], sds.shape[1:]
            fan_in = int(np.prod(per[:-1]))
            std = 1.0 / np.sqrt(fan_in)

            @jax.jit
            def one_layer(k, _per=per, _std=std):
                w = jax.random.normal(k, _per, jnp.float32) * _std
                q = quantize_tree_int4({"w": w}, min_size=1)["w"]
                return q["q4"], q["scale"]

            subkeys = jax.random.split(sub, L)
            q4s, scales = [], []
            for l in range(L):
                if over_budget():
                    raise TimeoutError(
                        f"budget {BUDGET_S:.0f}s spent mid-build "
                        f"(leaf {i}/{len(flat)}, layer {l}/{L})"
                    )
                if first_quant and l <= 1:
                    # time the compile call (l=0) and one steady call
                    # (l=1) synchronously; projection needs real wall
                    # clock, not async-dispatch time
                    t_one = time.perf_counter()
                    a, b = one_layer(subkeys[l])
                    jax.block_until_ready((a, b))
                    t_one = time.perf_counter() - t_one
                    if l == 0:
                        t_compile = t_one
                        # a single-layer leaf never reaches a steady
                        # call — project with t_call=t_compile, an
                        # overestimate, which errs toward aborting
                        t_call = t_compile if L == 1 else None
                    else:
                        t_call = t_one
                    if t_call is not None:
                        # remaining: this leaf's untimed layers + the
                        # other n_quant-1 leaves (compile + L-1 steady
                        # calls each); 1.2x for stacking/non-quant
                        # leaves
                        remaining = 1.2 * (
                            (L - 1 - l) * t_call
                            + (n_quant - 1)
                            * (t_compile + (L - 1) * t_call)
                        )
                        elapsed = time.time() - t0
                        finish = elapsed + remaining
                        ceiling = BUDGET_S - decode_reserve_s
                        log_fn(
                            f"build projection: per-leaf compile "
                            f"{t_compile:.1f}s, per-layer call "
                            f"{t_call * 1e3:.0f}ms x {n_quant} leaves "
                            f"x {L} layers -> finish ~{finish:.0f}s "
                            f"of {ceiling:.0f}s ceiling (budget "
                            f"{BUDGET_S:.0f}s - decode reserve "
                            f"{decode_reserve_s:.0f}s)"
                        )
                        # abort only when the caller declared a decode
                        # reserve — i.e. a timed chip run that must
                        # save window for the decode compile. The tiny
                        # layout pin (reserve 0) logs and carries on.
                        if decode_reserve_s > 0 and finish > ceiling:
                            raise BuildBudgetExceeded(
                                f"projected build finish {finish:.0f}s "
                                f"> ceiling {ceiling:.0f}s",
                                t_compile, t_call, n_quant, L,
                            )
                else:
                    a, b = one_layer(subkeys[l])
                q4s.append(a)
                scales.append(b)
            leaves.append(
                {"q4": jnp.stack(q4s), "scale": jnp.stack(scales)}
            )
            log_fn(
                f"leaf {p}: int4 {sds.shape} -> q4 "
                f"{leaves[-1]['q4'].shape}"
            )
        elif p.endswith("scale"):  # norm scales
            leaves.append(jnp.ones(sds.shape, jnp.bfloat16))
        elif p.endswith("bias"):
            leaves.append(jnp.zeros(sds.shape, jnp.bfloat16))
        else:  # embed / lm_head / unquantized kernels
            fan_in = sds.shape[-2] if sds.ndim >= 2 else sds.shape[-1]
            std = 0.02 if p.endswith("embedding") else 1.0 / np.sqrt(fan_in)
            gen = jax.jit(
                lambda k, _s=sds.shape, _std=std: (
                    jax.random.normal(k, _s, jnp.float32) * _std
                ).astype(jnp.bfloat16)
            )
            leaves.append(gen(sub))
            log_fn(f"leaf {p}: bf16 {sds.shape}")
    return jax.tree_util.tree_unflatten(treedef, leaves)


def check_layout_matches_pipeline(cfg_cls, model_cls, log_fn=lambda m: None):
    """Tiny-model pin: the on-device builder's tree must be structurally
    identical (paths, shapes, dtypes) to init + quantize_for_scan_dequant
    — the layout contract that makes the 8b run representative."""
    from pytorch_distributed_tpu.ops.quant import quantize_for_scan_dequant

    cfg = cfg_cls.tiny()
    cfg = __import__("dataclasses").replace(cfg, scan_dequant=True)
    model = model_cls(cfg)
    ids0 = jnp.zeros((1, 8), jnp.int32)
    built = build_int4_params(model, ids0, log_fn=log_fn)
    ref_params = model.init(jax.random.key(0), ids0)["params"]
    ref = quantize_for_scan_dequant(ref_params, "int4")

    def _quantized_leaf(tree, path):
        # structural test: a leaf belongs to a quantized kernel iff its
        # parent dict carries the sibling "q4" payload — never inferred
        # from the path suffix + dtype, which would silence a real
        # dtype drift in the quantizer's per-channel scales (ADVICE r4)
        node = tree
        for k in path[:-1]:
            node = node[k.key] if hasattr(k, "key") else node[k.idx]
        return isinstance(node, dict) and "q4" in node

    b_flat = jax.tree_util.tree_flatten_with_path(built)[0]
    r_flat = jax.tree_util.tree_flatten_with_path(ref)[0]
    assert len(b_flat) == len(r_flat), (len(b_flat), len(r_flat))
    for (bp, bl), (rp, rl) in zip(b_flat, r_flat):
        assert bp == rp, (bp, rp)
        assert bl.shape == rl.shape, (path_str(bp), bl.shape, rl.shape)
        # quantized payloads AND their per-channel scales must match the
        # pipeline's dtypes exactly; full-precision leaves (incl. norm
        # scales) rest in bf16 here vs the init tree's f32 (the at-rest
        # choice, not a layout difference)
        if _quantized_leaf(built, bp):
            assert bl.dtype == rl.dtype, (path_str(bp), bl.dtype, rl.dtype)
    return built, model, cfg


def main():
    global t0
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", choices=("8b", "tiny"), default="8b")
    ap.add_argument("--prompt-len", type=int, default=64)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--batch", type=int, default=1)
    args = ap.parse_args()

    if args.preset == "8b":
        # the 8b preset is a timed chip measurement: serialize behind
        # every other measuring run, and start the budget clock only
        # once at the front of the queue. The tiny preset is a
        # functional rehearsal (layout pin + CPU decode) — it takes no
        # lock, so the test suite can run it while a real bench holds
        # the core.
        from pytorch_distributed_tpu.utils.benchlock import (
            start_measurement,
        )

        _lock, t0 = start_measurement()  # noqa: F841 — held for life

    ptd.enable_compilation_cache()
    ptd.init_process_group()
    on_tpu = ptd.is_tpu()
    log(f"platform={ptd.platform()} preset={args.preset}")

    if args.preset == "8b" and not on_tpu:
        log(
            "8b preset needs the real chip (an 8B CPU decode is noise "
            "wearing a TPU metric name) — nothing to do"
        )
        return

    log("layout pin: builder tree == init+quantize_for_scan_dequant tree")
    built_tiny, tiny_model, tiny_cfg = check_layout_matches_pipeline(
        LlamaConfig, LlamaForCausalLM, log_fn=log
    )
    log("layout pin OK")

    depth_note = ""
    if args.preset == "tiny":
        cfg, model, params = tiny_cfg, tiny_model, built_tiny
        B, P, NEW = 2, 8, 8
        iters = 2
    else:
        import dataclasses

        reserve = float(os.environ.get("PTD_DECODE_RESERVE_S", "1200"))
        cfg = dataclasses.replace(
            LlamaConfig.llama3_8b(), scan_dequant=True
        )
        model = LlamaForCausalLM(cfg)
        B, P, NEW = args.batch, args.prompt_len, args.new_tokens
        iters = 3
        log("building 8B int4 tree on device, layer by layer...")
        try:
            params = build_int4_params(
                model, jnp.zeros((1, 8), jnp.int32), log_fn=log,
                decode_reserve_s=reserve,
            )
        except TimeoutError as e:
            log(f"budget spent mid-build ({e}) — stopping")
            return
        except BuildBudgetExceeded as e:
            # the window can't afford 32 layers — take the depth it CAN
            # afford rather than dying mid-build with no executed fact.
            # Same per-layer shapes -> the already-paid compile is
            # reused; only the layer loop shrinks.
            spendable = BUDGET_S - reserve - (time.time() - t0)
            per_leaf_fixed = e.n_quant * e.t_compile
            l_ok = int(
                (spendable / 1.2 - per_leaf_fixed)
                / max(e.n_quant * e.t_call, 1e-9)
            )
            l_ok = max(1, min(cfg.num_layers, l_ok))
            log(
                f"REDUCED DEPTH: full 32-layer build projected past the "
                f"window (compile {e.t_compile:.1f}s/leaf, call "
                f"{e.t_call * 1e3:.0f}ms/layer) — rebuilding at "
                f"num_layers={l_ok}; the metric will say so"
            )
            depth_note = f"_{l_ok}layers"
            cfg = dataclasses.replace(cfg, num_layers=l_ok)
            model = LlamaForCausalLM(cfg)
            try:
                params = build_int4_params(
                    model, jnp.zeros((1, 8), jnp.int32), log_fn=log,
                    decode_reserve_s=reserve,
                )
            except (BuildBudgetExceeded, TimeoutError) as e2:
                log(
                    f"even the reduced-depth build could not finish in "
                    f"the window ({e2}) — stopping with projection-only "
                    f"evidence"
                )
                return

    at_rest = quantized_bytes(params)
    log(f"params at rest: {at_rest / 1e9:.2f} GB")

    rng = np.random.default_rng(0)
    ids = jnp.asarray(
        rng.integers(cfg.vocab_size, size=(B, P)).astype(np.int32)
    )

    serving = Policy(
        param_dtype=jnp.bfloat16,
        compute_dtype=jnp.bfloat16,
        output_dtype=jnp.float32,
    )
    with use_policy(serving):
        run = jax.jit(
            lambda p, i: ptd.generate(
                model, p, i, max_new_tokens=NEW, temperature=0.0
            )
        )
        log(f"compiling + first decode (B={B} P={P} NEW={NEW})...")
        out = run(params, ids)
        int(out[0, -1])  # scalar fetch — the only real sync on the relay
    log("first decode done")

    if over_budget():
        log(f"budget spent before timing loop — stopping with compile-only"
            f" evidence")
        return

    t = time.perf_counter()
    for _ in range(iters):
        out = run(params, ids)
    int(out[0, -1])
    dt = (time.perf_counter() - t) / iters
    tok_per_sec = B * NEW / dt

    peak = ptd.max_memory_allocated()
    mem_note = ""
    try:
        ma = run.lower(params, ids).compile().memory_analysis()
        mem_note = (
            f" xla: args={ma.argument_size_in_bytes / 1e9:.2f}GB "
            f"temps={ma.temp_size_in_bytes / 1e9:.2f}GB "
            f"out={ma.output_size_in_bytes / 1e9:.2f}GB"
        )
    except Exception as e:
        mem_note = f" (memory_analysis unavailable: {type(e).__name__})"

    rec = {
        "metric": f"llama8b{depth_note}_int4_scan_decode_tokens_per_sec"
        if args.preset == "8b"
        else "llama_tiny_int4_scan_decode_tokens_per_sec",
        "value": round(tok_per_sec, 2),
        "unit": f"tokens/sec incl. prefill, int4+scan_dequant bf16, "
        f"batch={B} prompt={P} new={NEW}, {cfg.num_layers} layers",
        "vs_baseline": None,
        "platform": ptd.platform(),
        "at_rest_gb": round(at_rest / 1e9, 3),
        "hbm_peak_gb": round(peak / 1e9, 3) if peak else None,
    }
    print(json.dumps(rec), flush=True)
    log(
        f"decode: {tok_per_sec:.2f} tok/s ({dt * 1e3:.0f} ms/call), "
        f"at-rest {at_rest / 1e9:.2f} GB, peak HBM "
        f"{peak / 1e9:.2f} GB{mem_note}"
    )


if __name__ == "__main__":
    main()
