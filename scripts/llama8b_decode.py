"""First EXECUTED Llama-3-8B step: on-device int4 build + scan_dequant decode.

Recipe 5 (BASELINE.json:11, SURVEY.md §7 hard part c) is the one
blueprint row that has only ever been proven abstractly (AOT lowering,
v5p-64 fit, XLA-cost-analysis step projection — tests/test_llama8b.py,
BASELINE.md). This script turns it into an executed fact on the ONE
real chip: a full-architecture Llama-3-8B (128256 vocab, 32 scanned
layers, GQA 32/8, 14336 FFN) decoding real tokens through the
int4 + per-layer-scan-dequant serving path (ops/quant.py,
models/scan.py).

Why random weights are the honest play here: there is no egress to
fetch real checkpoints, and throughput/memory do not depend on weight
values. The weights are built DIRECTLY on device in the exact layout
``quantize_for_scan_dequant`` produces — never materializing a bf16/f32
8B tree anywhere (host RAM or HBM):

* scanned block kernels: per LAYER, generate one layer's f32 kernel on
  device, int4-quantize it there, free the float transient, stack the
  32 quantized slices. Groupwise int4 math is slice-invariant (scales
  reduce axis -2 per layer), so per-layer-quantize+stack is bitwise
  the layout the whole-tree quantizer emits on a stacked kernel — the
  tiny preset asserts exactly that against the real pipeline.
* everything else (embed, lm_head, norm scales) rests in bf16.

Memory budget on a 16 GB v5e: ~3.5 GB int4 payload + ~0.2 GB scales
+ ~2.1 GB bf16 embed+lm_head at rest; decode transiently reconstructs
ONE layer (~0.44 GB bf16 under Policy(param_dtype=bf16)) per scan tick.

Chip rules (docs/CHIP_PROTOCOL.md): no external kill timers; the script
budgets itself between phases/leaves via PTD_PROBE_BUDGET_S and exits
cleanly when over. The 8b preset refuses to run on CPU (a consumption
metric on the host would be noise wearing a TPU name); --preset tiny is
the CPU rehearsal path and is exercised by tests/test_llama8b.py.
"""

import argparse
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

t0 = time.time()
BUDGET_S = float(os.environ.get("PTD_PROBE_BUDGET_S", "2400"))


def log(msg):
    print(f"[{time.time() - t0:7.1f}s] {msg}", flush=True)


def over_budget():
    return time.time() - t0 > BUDGET_S


import jax
import jax.numpy as jnp
import numpy as np

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from pytorch_distributed_tpu.ops.quant import (
    quantize_tree_int4,
    quantized_bytes,
)
from pytorch_distributed_tpu.parallel.sharding import path_str
from pytorch_distributed_tpu.runtime.precision import Policy, use_policy

# mirror quantize_for_scan_dequant's gate: only kernels inside the
# scanned stack, judged on the STACKED leaf (that is what the real
# pipeline quantizes)
_INCLUDE = re.compile(r"/block/.*/kernel$")
_MIN_SIZE = 4096


def _quantizable(path: str, sds) -> bool:
    return (
        _INCLUDE.search("/" + path) is not None
        and sds.ndim >= 2
        and sds.size >= _MIN_SIZE
        and sds.shape[-1] % 2 == 0
    )


def build_int4_params(model, ids0, seed=0, log_fn=lambda m: None):
    """The model's params tree in quantize_for_scan_dequant's int4
    layout, built leaf-by-leaf ON DEVICE — peak float transient is one
    LAYER's largest kernel, never the whole tree."""
    shapes = jax.eval_shape(
        lambda k: model.init(k, ids0), jax.random.key(seed)
    )["params"]
    flat, treedef = jax.tree_util.tree_flatten_with_path(shapes)
    key = jax.random.key(seed + 1)
    leaves = []
    for i, (path, sds) in enumerate(flat):
        p = path_str(path)
        key, sub = jax.random.split(key)
        if _quantizable(p, sds):
            L, per = sds.shape[0], sds.shape[1:]
            fan_in = int(np.prod(per[:-1]))
            std = 1.0 / np.sqrt(fan_in)

            @jax.jit
            def one_layer(k, _per=per, _std=std):
                w = jax.random.normal(k, _per, jnp.float32) * _std
                q = quantize_tree_int4({"w": w}, min_size=1)["w"]
                return q["q4"], q["scale"]

            subkeys = jax.random.split(sub, L)
            q4s, scales = [], []
            for l in range(L):
                if over_budget():
                    raise TimeoutError(
                        f"budget {BUDGET_S:.0f}s spent mid-build "
                        f"(leaf {i}/{len(flat)}, layer {l}/{L})"
                    )
                a, b = one_layer(subkeys[l])
                q4s.append(a)
                scales.append(b)
            leaves.append(
                {"q4": jnp.stack(q4s), "scale": jnp.stack(scales)}
            )
            log_fn(
                f"leaf {p}: int4 {sds.shape} -> q4 "
                f"{leaves[-1]['q4'].shape}"
            )
        elif p.endswith("scale"):  # norm scales
            leaves.append(jnp.ones(sds.shape, jnp.bfloat16))
        elif p.endswith("bias"):
            leaves.append(jnp.zeros(sds.shape, jnp.bfloat16))
        else:  # embed / lm_head / unquantized kernels
            fan_in = sds.shape[-2] if sds.ndim >= 2 else sds.shape[-1]
            std = 0.02 if p.endswith("embedding") else 1.0 / np.sqrt(fan_in)
            gen = jax.jit(
                lambda k, _s=sds.shape, _std=std: (
                    jax.random.normal(k, _s, jnp.float32) * _std
                ).astype(jnp.bfloat16)
            )
            leaves.append(gen(sub))
            log_fn(f"leaf {p}: bf16 {sds.shape}")
    return jax.tree_util.tree_unflatten(treedef, leaves)


def check_layout_matches_pipeline(cfg_cls, model_cls):
    """Tiny-model pin: the on-device builder's tree must be structurally
    identical (paths, shapes, dtypes) to init + quantize_for_scan_dequant
    — the layout contract that makes the 8b run representative."""
    from pytorch_distributed_tpu.ops.quant import quantize_for_scan_dequant

    cfg = cfg_cls.tiny()
    cfg = __import__("dataclasses").replace(cfg, scan_dequant=True)
    model = model_cls(cfg)
    ids0 = jnp.zeros((1, 8), jnp.int32)
    built = build_int4_params(model, ids0)
    ref_params = model.init(jax.random.key(0), ids0)["params"]
    ref = quantize_for_scan_dequant(ref_params, "int4")

    b_flat = jax.tree_util.tree_flatten_with_path(built)[0]
    r_flat = jax.tree_util.tree_flatten_with_path(ref)[0]
    assert len(b_flat) == len(r_flat), (len(b_flat), len(r_flat))
    for (bp, bl), (rp, rl) in zip(b_flat, r_flat):
        assert bp == rp, (bp, rp)
        assert bl.shape == rl.shape, (path_str(bp), bl.shape, rl.shape)
        # quantized payloads/scales must match the pipeline's dtypes
        # exactly; full-precision leaves rest in bf16 here vs the init
        # tree's f32 (the at-rest choice, not a layout difference)
        if path_str(bp).endswith(("q4", "scale")) and bl.dtype != jnp.bfloat16:
            assert bl.dtype == rl.dtype, (path_str(bp), bl.dtype, rl.dtype)
    return built, model, cfg


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", choices=("8b", "tiny"), default="8b")
    ap.add_argument("--prompt-len", type=int, default=64)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--batch", type=int, default=1)
    args = ap.parse_args()

    ptd.enable_compilation_cache()
    ptd.init_process_group()
    on_tpu = ptd.is_tpu()
    log(f"platform={ptd.platform()} preset={args.preset}")

    if args.preset == "8b" and not on_tpu:
        log(
            "8b preset needs the real chip (an 8B CPU decode is noise "
            "wearing a TPU metric name) — nothing to do"
        )
        return

    log("layout pin: builder tree == init+quantize_for_scan_dequant tree")
    built_tiny, tiny_model, tiny_cfg = check_layout_matches_pipeline(
        LlamaConfig, LlamaForCausalLM
    )
    log("layout pin OK")

    if args.preset == "tiny":
        cfg, model, params = tiny_cfg, tiny_model, built_tiny
        B, P, NEW = 2, 8, 8
        iters = 2
    else:
        import dataclasses

        cfg = dataclasses.replace(
            LlamaConfig.llama3_8b(), scan_dequant=True
        )
        model = LlamaForCausalLM(cfg)
        B, P, NEW = args.batch, args.prompt_len, args.new_tokens
        iters = 3
        log("building 8B int4 tree on device, layer by layer...")
        params = build_int4_params(
            model, jnp.zeros((1, 8), jnp.int32), log_fn=log
        )

    at_rest = quantized_bytes(params)
    log(f"params at rest: {at_rest / 1e9:.2f} GB")

    rng = np.random.default_rng(0)
    ids = jnp.asarray(
        rng.integers(cfg.vocab_size, size=(B, P)).astype(np.int32)
    )

    serving = Policy(
        param_dtype=jnp.bfloat16,
        compute_dtype=jnp.bfloat16,
        output_dtype=jnp.float32,
    )
    with use_policy(serving):
        run = jax.jit(
            lambda p, i: ptd.generate(
                model, p, i, max_new_tokens=NEW, temperature=0.0
            )
        )
        log(f"compiling + first decode (B={B} P={P} NEW={NEW})...")
        out = run(params, ids)
        int(out[0, -1])  # scalar fetch — the only real sync on the relay
    log("first decode done")

    if over_budget():
        log(f"budget spent before timing loop — stopping with compile-only"
            f" evidence")
        return

    t = time.perf_counter()
    for _ in range(iters):
        out = run(params, ids)
    int(out[0, -1])
    dt = (time.perf_counter() - t) / iters
    tok_per_sec = B * NEW / dt

    peak = ptd.max_memory_allocated()
    mem_note = ""
    try:
        ma = run.lower(params, ids).compile().memory_analysis()
        mem_note = (
            f" xla: args={ma.argument_size_in_bytes / 1e9:.2f}GB "
            f"temps={ma.temp_size_in_bytes / 1e9:.2f}GB "
            f"out={ma.output_size_in_bytes / 1e9:.2f}GB"
        )
    except Exception as e:
        mem_note = f" (memory_analysis unavailable: {type(e).__name__})"

    rec = {
        "metric": f"llama8b_int4_scan_decode_tokens_per_sec"
        if args.preset == "8b"
        else "llama_tiny_int4_scan_decode_tokens_per_sec",
        "value": round(tok_per_sec, 2),
        "unit": f"tokens/sec incl. prefill, int4+scan_dequant bf16, "
        f"batch={B} prompt={P} new={NEW}",
        "vs_baseline": None,
        "platform": ptd.platform(),
        "at_rest_gb": round(at_rest / 1e9, 3),
        "hbm_peak_gb": round(peak / 1e9, 3) if peak else None,
    }
    print(json.dumps(rec), flush=True)
    log(
        f"decode: {tok_per_sec:.2f} tok/s ({dt * 1e3:.0f} ms/call), "
        f"at-rest {at_rest / 1e9:.2f} GB, peak HBM "
        f"{peak / 1e9:.2f} GB{mem_note}"
    )


if __name__ == "__main__":
    main()
