"""Flash-vs-XLA attention timing on the real chip (decides the dispatcher
default — ops/attention.py keeps flash opt-in until it demonstrably wins).

Times fwd and fwd+bwd for both paths at increasing sequence lengths,
chaining iterations inside one jitted lax.scan so the axon relay's
per-dispatch RTT amortizes away. Run ON THE CHIP ONLY.

IMPORTANT: never kill this process externally mid-compile — a killed
relay client wedges the chip lease for everyone (observed r2, BASELINE.md).
It budgets its own wall clock instead: once BUDGET_S is spent, remaining
shapes are skipped and it exits cleanly after the in-flight compile.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

t0 = time.time()
BUDGET_S = float(os.environ.get("PTD_PROBE_BUDGET_S", "900"))


def log(msg):
    print(f"[{time.time() - t0:8.1f}s] {msg}", flush=True)


def over_budget() -> bool:
    return time.time() - t0 > BUDGET_S


import jax
import jax.numpy as jnp
import numpy as np

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.ops.attention import dot_product_attention
from pytorch_distributed_tpu.ops.flash_attention import flash_attention

ITERS = 20
SHAPES = [  # (B, S, H, D)
    (8, 1024, 16, 64),   # GPT-2-medium bench shape
    (4, 2048, 16, 64),
    (2, 4096, 16, 64),
    (1, 8192, 16, 64),   # long-context: XLA materializes S^2 here
]


def timed(fn, q, k, v, label, flops):
    """Run fn ITERS times inside one scan; fetch one scalar at the end."""

    @jax.jit
    def loop(q, k, v):
        # carry the output (not a stacked history) so the timed loop holds
        # one buffer; feed a scalar back into q so iterations chain
        def body(o, _):
            o = fn(q + o[0, 0, 0, 0].astype(jnp.bfloat16) * 0, k, v)
            return o, None
        o0 = jnp.zeros_like(q)
        o, _ = jax.lax.scan(body, o0, None, length=ITERS)
        return o

    t = time.time()
    out = loop(q, k, v)
    float(out.astype(jnp.float32)[0, 0, 0, 0])
    compile_s = time.time() - t
    t = time.time()
    out = loop(q, k, v)
    float(out.astype(jnp.float32)[0, 0, 0, 0])
    dt = (time.time() - t) / ITERS
    log(f"  {label:10s} {dt * 1e3:7.2f}ms/iter  ~{flops / dt / 1e12:5.1f} "
        f"TFLOP/s  (compile {compile_s:.1f}s)")
    return dt


def grad_of(fn):
    def loss(q, k, v):
        return fn(q, k, v).astype(jnp.float32).sum()

    g = jax.grad(loss, argnums=(0, 1, 2))

    def fwdbwd(q, k, v):
        dq, dk, dv = g(q, k, v)
        # keep ALL THREE grads live: returning dq alone lets XLA
        # dead-code-eliminate the dk/dv backward matmuls, which would time
        # ~1/3 of a real backward for the XLA path while the fused flash
        # VJP kernel can't be partially eliminated — biasing the decision
        return dq + (dk.sum() + dv.sum()).astype(dq.dtype)

    return fwdbwd


def main():
    global t0
    from pytorch_distributed_tpu.utils.benchlock import start_measurement

    # lock BEFORE the budget clock starts: queue time behind another
    # run is not this run's measurement time
    _lock, t0 = start_measurement()  # noqa: F841 — held for life
    ptd.enable_compilation_cache()
    log(f"platform={ptd.platform()} kind={jax.devices()[0].device_kind}")
    xla = lambda q, k, v: dot_product_attention(q, k, v, causal=True)
    fla = lambda q, k, v: flash_attention(q, k, v, causal=True)
    for B, S, H, D in SHAPES:
        if over_budget():
            log(f"budget {BUDGET_S:.0f}s spent — skipping remaining shapes")
            break
        rng = np.random.default_rng(0)
        q, k, v = (
            jnp.asarray(rng.normal(size=(B, S, H, D)).astype(np.float32))
            .astype(jnp.bfloat16)
            for _ in range(3)
        )
        fwd_flops = 4 * B * H * S * S * D / 2  # causal
        bwd_flops = fwd_flops * 3.5  # fwd recompute + dq,dk,dv
        log(f"--- B={B} S={S} H={H} D={D}")
        for label, fn, flops in (
            ("xla fwd", xla, fwd_flops),
            ("flash fwd", fla, fwd_flops),
            ("xla bwd", grad_of(xla), bwd_flops),
            ("flash bwd", grad_of(fla), bwd_flops),
        ):
            if over_budget():
                log(f"budget {BUDGET_S:.0f}s spent — skipping {label}")
                continue
            try:
                timed(fn, q, k, v, label, flops)
            except Exception as e:
                log(f"  {label} FAILED: {type(e).__name__}: {e}")
    log("DONE")


if __name__ == "__main__":
    main()
