"""Kill-resume drill: crash a real recipe mid-run, resume it, prove it.

The in-process chaos suite (tests/test_chaos.py) injects faults around
library calls; this drill does the thing no unit test can — it KILLS the
whole training process (SIGKILL, or ``PTD_FAULTS`` ``mode=kill`` which is
``os._exit`` mid-save) at seeded-random moments, restarts it the way an
elastic agent would, and asserts the run still converges to its expected
final step with an intact, verifiable checkpoint.

Usage (CPU smoke, ~a minute warm):

    python scripts/chaos_drill.py --kills 2
    python scripts/chaos_drill.py --faults "ckpt.write_shard:mode=kill,after=2,count=1"
    python scripts/chaos_drill.py --recipe recipes/resnet18_cifar10.py \\
        --epochs 4 --steps-per-epoch 4 --batch-size 16

Exit code 0 = drill passed. Any recipe exposing ``--synthetic
--steps-per-epoch --epochs --batch-size --ckpt-dir --seed`` works
(resnet18_cifar10 is the default because it is the fastest smoke).
"""

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--recipe", default="recipes/resnet18_cifar10.py")
    p.add_argument("--ckpt-dir", default=None,
                   help="default: a fresh temp dir, removed on success")
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--steps-per-epoch", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--kills", type=int, default=2,
                   help="SIGKILLs at seeded-random moments before the "
                   "final undisturbed attempt")
    p.add_argument("--kill-window", type=float, nargs=2,
                   default=(3.0, 20.0), metavar=("MIN_S", "MAX_S"),
                   help="seconds after launch to fire each SIGKILL")
    p.add_argument("--faults", default=None,
                   help="PTD_FAULTS spec armed in the killed attempts "
                   "instead of parent-side SIGKILL (e.g. "
                   "'ckpt.write_shard:mode=kill,after=2,count=1')")
    p.add_argument("--max-attempts", type=int, default=8)
    return p.parse_args(argv)


def _child_cmd(args, ckpt_dir, metrics_path):
    return [
        sys.executable, os.path.join(REPO, args.recipe),
        "--synthetic",
        "--epochs", str(args.epochs),
        "--steps-per-epoch", str(args.steps_per_epoch),
        "--batch-size", str(args.batch_size),
        "--ckpt-dir", ckpt_dir,
        "--seed", str(args.seed),
        "--log-every", "1",
        # every attempt appends goodput/step records to ONE stream (the
        # MetricsWriter opens in append mode), so the drill can account
        # productive-vs-recovery seconds across kills and restarts
        "--metrics-path", metrics_path,
        # arm the span tracer too: the last surviving attempt's
        # trace.json (atomic export — a killed attempt can't tear it)
        # plus per-attempt span rollups in the same stream give
        # scripts/obs_report.py a step-phase breakdown for the drill
        "--trace-dir", ckpt_dir,
    ]


def main(argv=None):
    args = parse_args(argv)
    import numpy as np

    rng = np.random.default_rng(args.seed)
    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="chaos_drill_")
    owns_dir = args.ckpt_dir is None
    metrics_path = os.path.join(ckpt_dir, "drill_metrics.jsonl")
    cmd = _child_cmd(args, ckpt_dir, metrics_path)
    expected_final = args.epochs * args.steps_per_epoch
    kills_left = args.kills
    print(f"# drill: {' '.join(cmd)}", file=sys.stderr)
    t_drill0 = time.monotonic()

    ok = False
    for attempt in range(1, args.max_attempts + 1):
        env = dict(os.environ)
        kill_this_attempt = kills_left > 0
        delay = None
        if kill_this_attempt:
            if args.faults:
                env["PTD_FAULTS"] = args.faults
                env["PTD_FAULTS_SEED"] = str(args.seed + attempt)
            else:
                delay = float(rng.uniform(*args.kill_window))
        child = subprocess.Popen(
            cmd, env=env, cwd=REPO,
            stdout=sys.stderr, stderr=subprocess.STDOUT,
        )
        if delay is not None:
            try:
                child.wait(timeout=delay)
            except subprocess.TimeoutExpired:
                print(
                    f"# attempt {attempt}: SIGKILL after {delay:.1f}s",
                    file=sys.stderr,
                )
                child.send_signal(signal.SIGKILL)
        rc = child.wait()
        if kill_this_attempt:
            kills_left -= 1
            print(
                f"# attempt {attempt}: crashed as planned (rc={rc})",
                file=sys.stderr,
            )
            continue
        print(f"# attempt {attempt}: rc={rc}", file=sys.stderr)
        if rc == 0:
            ok = True
            break
        # EX_TEMPFAIL (preemption path) or a crash: restart like an agent
        time.sleep(1.0)

    from pytorch_distributed_tpu.train.checkpoint import (
        checkpoint_step,
        recover_stranded_checkpoints,
        verify_checkpoint,
    )

    recovered = recover_stranded_checkpoints(ckpt_dir)
    final_step = checkpoint_step(ckpt_dir)
    problems = verify_checkpoint(ckpt_dir)
    passed = (
        ok and final_step == expected_final and not problems
    )
    # goodput over the WHOLE drill wall clock: productive seconds come
    # from the surviving attempts' split="goodput" records (a killed
    # attempt's unflushed account is honestly lost — undercounting, not
    # inflating), the denominator charges restart gaps and killed
    # attempts too. read_metrics tolerates the torn final line the
    # mode=kill attempts leave behind.
    from pytorch_distributed_tpu.runtime.tracing import summarize_goodput
    from pytorch_distributed_tpu.train.metrics import read_metrics

    try:
        records = read_metrics(metrics_path)
    except OSError:
        records = []
    goodput = summarize_goodput(
        records, wall_s=time.monotonic() - t_drill0
    )
    print(json.dumps({
        "drill": "kill_resume",
        "recipe": args.recipe,
        "kills": args.kills,
        "faults": args.faults,
        "completed": ok,
        "final_checkpoint_step": final_step,
        "expected_final_step": expected_final,
        "verify_problems": problems,
        "post_recovered_tags": recovered,
        "goodput": goodput,
        "passed": passed,
    }))
    if passed and owns_dir:
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    elif not passed:
        print(f"# checkpoint dir kept for autopsy: {ckpt_dir}",
              file=sys.stderr)
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
