"""Kill-resume drill: crash a real recipe mid-run, resume it, prove it.

The in-process chaos suite (tests/test_chaos.py) injects faults around
library calls; this drill does the thing no unit test can — it KILLS the
whole training process (SIGKILL, or ``PTD_FAULTS`` ``mode=kill`` which is
``os._exit`` mid-save) at seeded-random moments, restarts it the way an
elastic agent would, and asserts the run still converges to its expected
final step with an intact, verifiable checkpoint.

Usage (CPU smoke, ~a minute warm):

    python scripts/chaos_drill.py --kills 2
    python scripts/chaos_drill.py --faults "ckpt.write_shard:mode=kill,after=2,count=1"
    python scripts/chaos_drill.py --recipe recipes/resnet18_cifar10.py \\
        --epochs 4 --steps-per-epoch 4 --batch-size 16

Exit code 0 = drill passed. Any recipe exposing ``--synthetic
--steps-per-epoch --epochs --batch-size --ckpt-dir --seed`` works
(resnet18_cifar10 is the default because it is the fastest smoke).
"""

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--drill", choices=("kill_resume", "resize",
                                       "ckpt_shard", "hang", "pipeline"),
                   default="kill_resume",
                   help="kill_resume: SIGKILL the whole training process "
                   "and restart it from disk (the original drill). "
                   "resize: SIGKILL one RANK of a multi-process elastic "
                   "world mid-run, assert the survivors re-mesh "
                   "IN-PROCESS and finish bit-identical to an unresized "
                   "reference, then grow back to full world and assert "
                   "the same (train/elastic_world.py). "
                   "ckpt_shard: kill one rank MID-DISTRIBUTED-SAVE "
                   "(after its shards, before its per-rank COMMIT), "
                   "assert the torn epoch reads as absent, restart the "
                   "whole world, and assert it restores the newest "
                   "world-COMPLETE epoch and finishes bit-identical to "
                   "an uninterrupted reference (train/ckpt_io.py). "
                   "hang: one rank of a live ring silently desyncs "
                   "(comm.hang mode=skip — no crash, no error, it just "
                   "stops showing up), every survivor must hit its "
                   "collective deadline, dump its flight ring, and the "
                   "merged autopsy must name the victim and the "
                   "diverging seq/op (runtime/flightrec.py). "
                   "pipeline: one STAGE of a live 2-stage host 1F1B "
                   "pipeline dies mid-schedule (pipeline.stage_stall "
                   "mode=kill at a specific (stage, op, microbatch)), "
                   "the surviving stage must hit its handoff deadline, "
                   "dump its flight ring, and the autopsy must convict "
                   "the dead stage from the survivor's dump alone "
                   "(parallel/pipeline_schedule.py)")
    p.add_argument("--world", type=int, default=3,
                   help="[resize] genesis world size")
    p.add_argument("--total-steps", type=int, default=36,
                   help="[resize] steps every survivor must reach")
    p.add_argument("--kill-after", type=int, default=8,
                   help="[resize] victim dies at this step boundary")
    p.add_argument("--step-delay-s", type=float, default=0.12,
                   help="[resize] synthetic per-step compute")
    p.add_argument("--ring-timeout-s", type=float, default=2.5,
                   help="[resize] collective deadline = detection bound")
    p.add_argument("--replication", type=int, default=2,
                   help="[resize] optimizer-shard copies (1 forces the "
                   "disk-fallback + replay path)")
    p.add_argument("--recipe", default="recipes/resnet18_cifar10.py")
    p.add_argument("--ckpt-dir", default=None,
                   help="default: a fresh temp dir, removed on success")
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--steps-per-epoch", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--kills", type=int, default=2,
                   help="SIGKILLs at seeded-random moments before the "
                   "final undisturbed attempt")
    p.add_argument("--kill-window", type=float, nargs=2,
                   default=(3.0, 20.0), metavar=("MIN_S", "MAX_S"),
                   help="seconds after launch to fire each SIGKILL")
    p.add_argument("--faults", default=None,
                   help="PTD_FAULTS spec armed in the killed attempts "
                   "instead of parent-side SIGKILL (e.g. "
                   "'ckpt.write_shard:mode=kill,after=2,count=1')")
    p.add_argument("--max-attempts", type=int, default=8)
    return p.parse_args(argv)


def _child_cmd(args, ckpt_dir, metrics_path):
    return [
        sys.executable, os.path.join(REPO, args.recipe),
        "--synthetic",
        "--epochs", str(args.epochs),
        "--steps-per-epoch", str(args.steps_per_epoch),
        "--batch-size", str(args.batch_size),
        "--ckpt-dir", ckpt_dir,
        "--seed", str(args.seed),
        "--log-every", "1",
        # every attempt appends goodput/step records to ONE stream (the
        # MetricsWriter opens in append mode), so the drill can account
        # productive-vs-recovery seconds across kills and restarts
        "--metrics-path", metrics_path,
        # arm the span tracer too: the last surviving attempt's
        # trace.json (atomic export — a killed attempt can't tear it)
        # plus per-attempt span rollups in the same stream give
        # scripts/obs_report.py a step-phase breakdown for the drill
        "--trace-dir", ckpt_dir,
    ]


def resize_main(args):
    """The shrink/grow drill: one rank SIGKILLed mid-run, survivors must
    re-mesh in-process (no process restart) and finish with params
    bit-identical to an unresized reference world on the same global
    data order; a replacement then joins and must land on the same bits.
    """
    from pytorch_distributed_tpu.launch import ElasticWorldLauncher
    from pytorch_distributed_tpu.train.elastic_world import (
        ElasticConfig,
        reference_run,
    )

    base = args.ckpt_dir or tempfile.mkdtemp(prefix="resize_drill_")
    owns_dir = args.ckpt_dir is None
    ckpt_dir = os.path.join(base, "ckpt")
    t0 = time.monotonic()
    launcher = ElasticWorldLauncher(
        os.path.join(base, "rdv"),
        worker_args=(
            "--total-steps", str(args.total_steps),
            "--global-batch", "16", "--microshards", "4",
            "--ckpt-dir", ckpt_dir, "--ckpt-every", "8",
            "--replication", str(args.replication),
            "--data-seed", str(args.seed),
            "--step-delay-s", str(args.step_delay_s),
            "--ring-timeout-s", str(args.ring_timeout_s),
            "--metrics-path", os.path.join(base, "metrics.jsonl"),
        ),
    )
    ids = [f"w{i}" for i in range(args.world)]
    victim = ids[-1]
    launcher.start_world(ids, env_overrides={victim: {
        # the deterministic departure: mode=kill at the elastic.peer_lost
        # step-boundary site — an os._exit, SIGKILL-grade
        "PTD_FAULTS": (
            f"elastic.peer_lost:mode=kill,after={args.kill_after}"
        ),
        "PTD_FAULTS_SEED": str(args.seed),
    }})
    # grow back only after the SHRUNKEN view has committed (the view-*.
    # json audit records the survivors' rank 0 writes) — otherwise the
    # death and the join coalesce into one 3->3 transition and the drill
    # never observes the shrink it is supposed to prove
    def committed_worlds():
        out = {}
        rdv = os.path.join(base, "rdv")
        for name in os.listdir(rdv):
            if name.startswith("view-") and name.endswith(".json"):
                try:
                    with open(os.path.join(rdv, name)) as f:
                        rec = json.load(f)
                    out[int(rec["epoch"])] = int(rec["world_size"])
                except (OSError, ValueError, KeyError):
                    continue
        return out

    deadline = time.monotonic() + 90
    while launcher.procs[victim].poll() is None:
        if time.monotonic() > deadline:
            break
        time.sleep(0.1)
    victim_rc = launcher.procs[victim].poll()
    while time.monotonic() < deadline:
        if (args.world - 1) in committed_worlds().values():
            break
        time.sleep(0.1)
    joiner = f"w{args.world}"
    launcher.add_worker(joiner)
    codes = launcher.wait(240)
    results = launcher.results()
    survivors = [w for w in ids if w != victim] + [joiner]

    ref = reference_run(ElasticConfig(
        total_steps=args.total_steps,
        replication=args.replication, data_seed=args.seed,
    ))
    crcs = {w: results.get(w, {}).get("params_crc") for w in survivors}
    bit_exact = all(c == ref["params_crc"] for c in crcs.values())
    finished = all(
        results.get(w, {}).get("final_step") == args.total_steps
        for w in survivors
    )
    shrank = any(
        v["world_size"] == args.world - 1
        for w in survivors for v in results.get(w, {}).get("views", [])
    )
    regrew = any(
        v["world_size"] == args.world and v["epoch"] > 1
        for w in survivors for v in results.get(w, {}).get("views", [])
    )
    no_restart = all(codes.get(w) == 0 for w in survivors)
    from pytorch_distributed_tpu.train.checkpoint import (
        resolve_tag,
        verify_checkpoint,
    )

    # sharded saves are step-tagged (full-format keeps 'latest'):
    # resolve the newest restorable tag, whichever format wrote it
    tag = resolve_tag(ckpt_dir)
    problems = (
        verify_checkpoint(ckpt_dir, tag) if tag is not None
        else ["no restorable checkpoint found"]
    )
    resize_log = []
    for w in survivors:
        for rec in results.get(w, {}).get("resizes", []):
            resize_log.append({"worker": w, **rec})
    goodput = {
        w: results.get(w, {}).get("goodput", {}) for w in survivors
    }
    passed = (
        bit_exact and finished and shrank and regrew and no_restart
        and victim_rc not in (0, None) and not problems
    )
    print(json.dumps({
        "drill": "resize",
        "world": args.world,
        "victim": victim,
        "victim_rc": victim_rc,
        "exit_codes": codes,
        "completed": finished,
        "shrank": shrank,
        "regrew": regrew,
        "bit_exact_vs_reference": bit_exact,
        "reference_params_crc": ref["params_crc"],
        "params_crc": crcs,
        "resizes": resize_log,
        "resize_goodput": {
            w: round(g.get("resize_s", 0.0), 3)
            for w, g in goodput.items()
        },
        "goodput": goodput,
        "verify_problems": problems,
        "wall_s": round(time.monotonic() - t0, 2),
        "passed": passed,
    }))
    if passed and owns_dir:
        shutil.rmtree(base, ignore_errors=True)
    elif not passed:
        print(f"# drill dir kept for autopsy: {base}", file=sys.stderr)
    return 0 if passed else 1


def ckpt_shard_main(args):
    """The mid-distributed-save drill: one rank of a sharded-checkpoint
    world is killed AFTER writing its shard files but BEFORE its
    per-rank COMMIT (the ``ckpt.rank_commit`` site, ``mode=kill``). The
    two-phase protocol must make that torn epoch read as ABSENT: a
    restarted world restores the newest world-COMPLETE epoch instead,
    replays, and finishes bit-identical to an uninterrupted reference.
    """
    from pytorch_distributed_tpu.launch import ElasticWorldLauncher
    from pytorch_distributed_tpu.train import ckpt_io
    from pytorch_distributed_tpu.train.elastic import EX_TEMPFAIL
    from pytorch_distributed_tpu.train.elastic_world import (
        ElasticConfig,
        reference_run,
    )

    base = args.ckpt_dir or tempfile.mkdtemp(prefix="ckpt_shard_drill_")
    owns_dir = args.ckpt_dir is None
    ckpt_dir = os.path.join(base, "ckpt")
    t0 = time.monotonic()
    ckpt_every = 3
    # the victim's rank_commit hit sequence: genesis save (hit 1), then
    # one per cadence save — after=2 fires on hit 3, i.e. mid-save at
    # step 2*ckpt_every, leaving step-<ckpt_every> the newest COMPLETE
    kill_hits = 2
    torn_step = 2 * ckpt_every
    complete_step = ckpt_every
    worker_args = (
        "--total-steps", str(args.total_steps),
        "--global-batch", "16", "--microshards", "4",
        "--ckpt-dir", ckpt_dir, "--ckpt-every", str(ckpt_every),
        "--ckpt-format", "sharded",
        "--replication", str(args.replication),
        "--data-seed", str(args.seed),
        "--on-peer-loss", "exit",
        "--ring-timeout-s", str(args.ring_timeout_s),
        "--metrics-path", os.path.join(base, "metrics.jsonl"),
    )
    ids = [f"w{i}" for i in range(args.world)]
    victim = ids[-1]
    launcher = ElasticWorldLauncher(
        os.path.join(base, "rdv"), worker_args=worker_args
    )
    launcher.start_world(ids, env_overrides={victim: {
        "PTD_FAULTS": (
            f"ckpt.rank_commit:mode=kill,count=1,after={kill_hits}"
        ),
        "PTD_FAULTS_SEED": str(args.seed),
    }})
    codes1 = launcher.wait(120)
    victim_rc = codes1.get(victim)
    interrupted = victim_rc not in (0, None) and all(
        codes1.get(w) not in (0, None) for w in ids
    )

    # the on-disk state the restart will see: the torn epoch's .tmp has
    # no WORLD_COMMIT and must read as absent; the newest restorable tag
    # is the last world-COMPLETE epoch
    torn_tmps = sorted(
        n for n in os.listdir(ckpt_dir) if n.endswith(".tmp")
    ) if os.path.isdir(ckpt_dir) else []
    torn_is_absent = all(
        ckpt_io._read_world_commit(os.path.join(ckpt_dir, n)) is None
        for n in torn_tmps
    )
    newest_tag = ckpt_io.resolve_tag(ckpt_dir)
    newest_step = (
        ckpt_io.checkpoint_step(ckpt_dir, newest_tag)
        if newest_tag is not None else None
    )

    # restart the whole world, clean, against the same checkpoint dir
    # (fresh rendezvous: the die-and-restore baseline's agent would)
    ids2 = [f"r{i}" for i in range(args.world)]
    launcher2 = ElasticWorldLauncher(
        os.path.join(base, "rdv2"), worker_args=worker_args
    )
    launcher2.start_world(ids2)
    codes2 = launcher2.wait(240)
    results = launcher2.results()

    ref = reference_run(ElasticConfig(
        total_steps=args.total_steps,
        replication=args.replication, data_seed=args.seed,
    ))
    crcs = {w: results.get(w, {}).get("params_crc") for w in ids2}
    bit_exact = all(c == ref["params_crc"] for c in crcs.values())
    finished = all(
        results.get(w, {}).get("final_step") == args.total_steps
        and codes2.get(w) == 0
        for w in ids2
    )
    ckpt_stats = {
        w: results.get(w, {}).get("ckpt", {}) for w in ids2
    }
    restored = all(
        s.get("restores", 0) >= 1 and s.get("walked_back", 0) == 0
        for s in ckpt_stats.values()
    )
    passed = (
        interrupted
        and bool(torn_tmps) and torn_is_absent
        and newest_step == complete_step
        and restored and finished and bit_exact
    )
    print(json.dumps({
        "drill": "ckpt_shard",
        "world": args.world,
        "victim": victim,
        "victim_rc": victim_rc,
        "survivor_rc_expected": EX_TEMPFAIL,
        "exit_codes_interrupted": codes1,
        "torn_tmp_dirs": torn_tmps,
        "torn_step_expected": torn_step,
        "torn_reads_absent": torn_is_absent,
        "newest_complete_tag": newest_tag,
        "newest_complete_step": newest_step,
        "restart_exit_codes": codes2,
        "restored": restored,
        "ckpt_stats": ckpt_stats,
        "completed": finished,
        "bit_exact_vs_reference": bit_exact,
        "reference_params_crc": ref["params_crc"],
        "params_crc": crcs,
        "wall_s": round(time.monotonic() - t0, 2),
        "passed": passed,
    }))
    if passed and owns_dir:
        shutil.rmtree(base, ignore_errors=True)
    elif not passed:
        print(f"# drill dir kept for autopsy: {base}", file=sys.stderr)
    return 0 if passed else 1


def hang_main(args):
    """The silent-desync drill: a 4-rank shm ring runs clean collective
    rounds, then one rank arms ``comm.hang:mode=skip`` and silently
    drops out of the next all_reduce — no crash, no error, the worst
    failure shape a fleet sees. Every survivor must hit its 2s
    collective deadline, dump its flight ring (``flight-rank<r>.json``),
    and the merged ``hang_autopsy`` verdict must name the victim rank
    and the diverging seq/op. The victim leaves NO dump by design — a
    desynced rank's absence IS the evidence.
    """
    import multiprocessing as mp

    from pytorch_distributed_tpu.runtime import flightrec
    from tests.flight_workers import WARMUP_ROUNDS, hang_worker

    base = args.ckpt_dir or tempfile.mkdtemp(prefix="hang_drill_")
    owns_dir = args.ckpt_dir is None
    t0 = time.monotonic()
    world = 4
    victim = world - 1
    spec = "comm.hang:mode=skip"
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=hang_worker,
                    args=(r, world, "hangdrill", q, base, victim, spec))
        for r in range(world)
    ]
    for p in procs:
        p.start()
    reports = {}
    for _ in range(world):
        rank, payload = q.get(timeout=120)
        reports[rank] = payload
    for p in procs:
        p.join(timeout=30)
    worker_errs = {r: p["err"] for r, p in reports.items()
                   if p["role"] == "?" or (p["role"] == "victim"
                                           and p["err"])}
    survivors = sorted(r for r in range(world) if r != victim)
    all_dumped = all(
        reports.get(r, {}).get("dump") is not None for r in survivors
    )
    dumps = flightrec.load_dumps(base) if os.path.isdir(base) else {}
    verdict = flightrec.autopsy(dumps)
    # the victim may or may not wedge itself after the skip — both
    # missing_rank (it left no dump) and mismatch (it logged a diverging
    # op before dying) name the same culprit with seq/op evidence
    named = (
        verdict["verdict"] in ("missing_rank", "mismatch")
        and verdict["victim_rank"] == victim
        and verdict["seq"] is not None
        and verdict["op"] is not None
    )
    # the survivors completed WARMUP_ROUNDS clean rounds before the
    # divergence, so the autopsy must point past them, not at round 0
    deep_enough = all(
        len(d.get("records", [])) > WARMUP_ROUNDS for d in dumps.values()
    )
    passed = (
        not worker_errs and all_dumped and named and deep_enough
        and victim not in dumps
    )
    print(json.dumps({
        "drill": "hang",
        "world": world,
        "victim": victim,
        "fault": spec,
        "survivor_dumps": {
            r: reports.get(r, {}).get("dump") for r in survivors
        },
        "victim_dumped": victim in dumps,
        "worker_errors": worker_errs,
        "verdict": verdict,
        "wall_s": round(time.monotonic() - t0, 2),
        "passed": passed,
    }))
    if passed and owns_dir:
        shutil.rmtree(base, ignore_errors=True)
    elif not passed:
        print(f"# drill dir kept for autopsy: {base}", file=sys.stderr)
    return 0 if passed else 1


def pipeline_main(args):
    """The dead-stage drill: a 2-stage host 1F1B pipeline trains over
    the real ring; stage 1 arms ``pipeline.stage_stall:mode=kill`` at a
    specific ``s1.bwd.m1`` op and dies there (os._exit — SIGKILL-grade,
    no dump). Stage 0 must hit its 2s handoff deadline, raise with its
    last completed flight named, and dump its ring; the autopsy must
    convict the dead stage from the survivor's dump alone. The victim
    leaves NO dump by design — the absent stage IS the evidence.
    """
    from pytorch_distributed_tpu.runtime import flightrec
    from tests.pipeline_workers import (
        pipeline_drill_worker,
        run_pipeline_world,
    )

    base = args.ckpt_dir or tempfile.mkdtemp(prefix="pipeline_drill_")
    owns_dir = args.ckpt_dir is None
    t0 = time.monotonic()
    world, victim = 2, 1
    spec = "pipeline.stage_stall:mode=kill,match=s1.bwd.m1"
    # the victim never reports (os._exit mid-schedule): expect only the
    # survivor's queue entry
    reports = dict(run_pipeline_world(
        world, pipeline_drill_worker,
        extra_args=(base, victim, spec), timeout=120.0, expect=1,
    ))
    survivor = reports.get(0, {})
    worker_errs = {
        r: p["error"] for r, p in reports.items() if "error" in p
    }
    survived = (
        survivor.get("role") == "survivor"
        and survivor.get("dumped") is True
        and "last completed flight" in survivor.get("err", "")
    )
    dumps = flightrec.load_dumps(base) if os.path.isdir(base) else {}
    verdict = flightrec.autopsy(dumps)
    named = (
        verdict["verdict"] == "missing_rank"
        and verdict["victim_rank"] == victim
    )
    passed = (
        not worker_errs and survived and named
        and victim not in dumps
    )
    print(json.dumps({
        "drill": "pipeline",
        "world": world,
        "victim_stage": victim,
        "fault": spec,
        "survivor_err": survivor.get("err"),
        "survivor_dumped": survivor.get("dumped"),
        "victim_dumped": victim in dumps,
        "worker_errors": worker_errs,
        "verdict": verdict,
        "wall_s": round(time.monotonic() - t0, 2),
        "passed": passed,
    }))
    if passed and owns_dir:
        shutil.rmtree(base, ignore_errors=True)
    elif not passed:
        print(f"# drill dir kept for autopsy: {base}", file=sys.stderr)
    return 0 if passed else 1


def main(argv=None):
    args = parse_args(argv)
    if args.drill == "resize":
        return resize_main(args)
    if args.drill == "ckpt_shard":
        return ckpt_shard_main(args)
    if args.drill == "hang":
        return hang_main(args)
    if args.drill == "pipeline":
        return pipeline_main(args)
    import numpy as np

    rng = np.random.default_rng(args.seed)
    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="chaos_drill_")
    owns_dir = args.ckpt_dir is None
    metrics_path = os.path.join(ckpt_dir, "drill_metrics.jsonl")
    cmd = _child_cmd(args, ckpt_dir, metrics_path)
    expected_final = args.epochs * args.steps_per_epoch
    kills_left = args.kills
    print(f"# drill: {' '.join(cmd)}", file=sys.stderr)
    t_drill0 = time.monotonic()

    ok = False
    for attempt in range(1, args.max_attempts + 1):
        env = dict(os.environ)
        kill_this_attempt = kills_left > 0
        delay = None
        if kill_this_attempt:
            if args.faults:
                env["PTD_FAULTS"] = args.faults
                env["PTD_FAULTS_SEED"] = str(args.seed + attempt)
            else:
                delay = float(rng.uniform(*args.kill_window))
        child = subprocess.Popen(
            cmd, env=env, cwd=REPO,
            stdout=sys.stderr, stderr=subprocess.STDOUT,
        )
        if delay is not None:
            try:
                child.wait(timeout=delay)
            except subprocess.TimeoutExpired:
                print(
                    f"# attempt {attempt}: SIGKILL after {delay:.1f}s",
                    file=sys.stderr,
                )
                child.send_signal(signal.SIGKILL)
        rc = child.wait()
        if kill_this_attempt:
            kills_left -= 1
            print(
                f"# attempt {attempt}: crashed as planned (rc={rc})",
                file=sys.stderr,
            )
            continue
        print(f"# attempt {attempt}: rc={rc}", file=sys.stderr)
        if rc == 0:
            ok = True
            break
        # EX_TEMPFAIL (preemption path) or a crash: restart like an agent
        time.sleep(1.0)

    from pytorch_distributed_tpu.train.checkpoint import (
        checkpoint_step,
        recover_stranded_checkpoints,
        resolve_tag,
        verify_checkpoint,
    )

    recovered = recover_stranded_checkpoints(ckpt_dir)
    tag = resolve_tag(ckpt_dir) or "latest"
    final_step = checkpoint_step(ckpt_dir, tag)
    problems = verify_checkpoint(ckpt_dir, tag)
    passed = (
        ok and final_step == expected_final and not problems
    )
    # goodput over the WHOLE drill wall clock: productive seconds come
    # from the surviving attempts' split="goodput" records (a killed
    # attempt's unflushed account is honestly lost — undercounting, not
    # inflating), the denominator charges restart gaps and killed
    # attempts too. read_metrics tolerates the torn final line the
    # mode=kill attempts leave behind.
    from pytorch_distributed_tpu.runtime.tracing import summarize_goodput
    from pytorch_distributed_tpu.train.metrics import read_metrics

    try:
        records = read_metrics(metrics_path)
    except OSError:
        records = []
    goodput = summarize_goodput(
        records, wall_s=time.monotonic() - t_drill0
    )
    print(json.dumps({
        "drill": "kill_resume",
        "recipe": args.recipe,
        "kills": args.kills,
        "faults": args.faults,
        "completed": ok,
        "final_checkpoint_step": final_step,
        "expected_final_step": expected_final,
        "verify_problems": problems,
        "post_recovered_tags": recovered,
        "goodput": goodput,
        "passed": passed,
    }))
    if passed and owns_dir:
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    elif not passed:
        print(f"# checkpoint dir kept for autopsy: {ckpt_dir}",
              file=sys.stderr)
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
