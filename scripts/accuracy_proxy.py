"""Accuracy proxy ON THE CHIP (VERDICT r2 ask #9).

The north star is throughput *at reference top-1* (BASELINE.json:5), but
no real dataset exists on this box (no network; `load_cifar10` finds no
pickles — BASELINE.md declares the offline ceiling). This script pins the
strongest available substitute: the FULL recipe-1 stack — ResNet-18
(cifar stem), SGD+momentum+weight-decay, cosine schedule, Trainer /
DataLoader / DistributedSampler / eval loop — trained on a CIFAR-shaped
learnable synthetic task on the real TPU, to a pinned eval accuracy.

Task: 32x32x3 noise images; the class (of 10) is the location of a
brightened 8x8 patch on a fixed 10-position grid, plus a channel tint —
linearly non-trivial, conv-learnable, and impossible to score above
chance by luck at n=1000 eval images (binomial p << 1e-100 at 0.9).

Chip protocol: internal wall-clock budget only (PTD_PROBE_BUDGET_S);
NEVER kill this process externally (docs/CHIP_PROTOCOL.md).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

t0 = time.time()
BUDGET_S = float(os.environ.get("PTD_PROBE_BUDGET_S", "900"))

import numpy as np


def make_task(n, seed):
    """10-class patch-position task at CIFAR shapes."""
    rng = np.random.default_rng(seed)
    imgs = rng.normal(0.0, 0.25, size=(n, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(10, size=n).astype(np.int32)
    # 10 patch anchors on a grid (4 corners, 4 edges, 2 center slots)
    anchors = [(0, 0), (0, 12), (0, 24), (12, 0), (12, 24),
               (24, 0), (24, 12), (24, 24), (8, 8), (16, 16)]
    for i, c in enumerate(labels):
        y, x = anchors[c]
        imgs[i, y:y + 8, x:x + 8, c % 3] += 1.0
    return imgs, labels


def main():
    global t0
    from pytorch_distributed_tpu.utils.benchlock import start_measurement

    # lock BEFORE the budget clock starts: queue time behind another
    # run is not this run's measurement time
    _lock, t0 = start_measurement()  # noqa: F841 — held for life
    import jax
    import optax

    import pytorch_distributed_tpu as ptd
    from pytorch_distributed_tpu.data import ArrayDataset, DataLoader
    from pytorch_distributed_tpu.models import ResNet18
    from pytorch_distributed_tpu.parallel import DataParallel
    from pytorch_distributed_tpu.runtime.mesh import MeshSpec
    from pytorch_distributed_tpu import optim
    from pytorch_distributed_tpu.train import (
        Trainer,
        TrainerConfig,
        TrainState,
        build_train_step,
        classification_eval_step,
        classification_loss_fn,
    )

    ptd.enable_compilation_cache()
    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1))
    platform = jax.devices()[0].platform
    if time.time() - t0 > BUDGET_S:
        print(f"# backend init alone ate the {BUDGET_S:.0f}s budget — "
              "relay unhealthy; not starting the run", flush=True)
        return 2
    epochs = int(os.environ.get("PTD_PROXY_EPOCHS", "6"))
    n_train = int(os.environ.get("PTD_PROXY_N", "8192"))  # CPU smoke knob

    imgs, labels = make_task(n_train, seed=0)
    eval_imgs, eval_labels = make_task(1000, seed=99)

    model = ResNet18(num_classes=10, stem="cifar")
    variables = model.init(jax.random.key(0), imgs[:1])
    batch = 256
    steps_per_epoch = len(imgs) // batch
    tx = optim.SGD(
        lr=optim.CosineAnnealingLR(0.1, T_max=epochs * steps_per_epoch),
        momentum=0.9, weight_decay=5e-4,
    )
    state = TrainState.create(
        apply_fn=model.apply, params=variables["params"],
        tx=tx, batch_stats=variables.get("batch_stats"),
    )
    strategy = DataParallel()
    train_loader = DataLoader(
        ArrayDataset(image=imgs, label=labels), batch,
        sharding=strategy.batch_sharding(),
    )
    eval_loader = DataLoader(
        ArrayDataset(image=eval_imgs, label=eval_labels), 250,
        shuffle=False, sharding=strategy.batch_sharding(),
    )
    trainer = Trainer(
        state, strategy,
        build_train_step(classification_loss_fn(model)),
        train_loader,
        eval_step=classification_eval_step(model),
        eval_loader=eval_loader,
        config=TrainerConfig(epochs=epochs, log_every=0,
                             handle_preemption=False),
    )
    # one fit() call drives all epochs (per-epoch shuffle + eval). The
    # device work is seconds; the genuinely unbounded stage is the first
    # jitted compile inside fit() against a wedged relay, and per
    # docs/CHIP_PROTOCOL.md that is ACCEPTED risk — a compile may not be
    # aborted (killing the client wedges the lease), so no budget check
    # can run between here and the first step. PTD_PROBE_BUDGET_S above
    # only gates starting at all after a slow backend init.
    trainer.fit()
    acc = float(trainer.last_eval_metrics.get("accuracy", 0.0))
    print(f"[{time.time() - t0:7.1f}s] {epochs} epochs "
          f"({epochs * steps_per_epoch} steps) final eval_acc={acc:.4f}",
          flush=True)

    result = {
        "metric": "accuracy_proxy_resnet18_synthetic_top1",
        "value": round(acc, 4),
        "unit": f"eval top-1, 10-class synthetic CIFAR-shape, "
                f"{epochs}x{steps_per_epoch} steps, batch {batch}",
        "platform": platform,
        "pinned_threshold": 0.99,
        "pass": bool(acc >= 0.99),
        "wall_s": round(time.time() - t0, 1),
    }
    print(json.dumps(result), flush=True)
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
