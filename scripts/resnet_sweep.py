"""ResNet-50 single-chip throughput sweep (VERDICT r1 #6: raise MFU).

Sweeps per-chip batch size and image layout knobs on the real chip with
MFU from XLA's cost analysis, and optionally captures a profiler trace of
the best configuration (--trace DIR). Run ON THE CHIP ONLY.
"""

import argparse
import os
import sys
import time

# repo root: the package is not pip-installed, and bench.py (for
# _resnet50_train_setup) is a repo-root module
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

t0 = time.time()


def log(msg):
    print(f"[{time.time() - t0:8.1f}s] {msg}", flush=True)


import jax
import jax.numpy as jnp
import numpy as np

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.runtime.device import compiled_flops, peak_flops


def bench_batch(batch: int, image: int = 224, iters: int = 50,
                stem: str = "imagenet"):
    from bench import _resnet50_train_setup

    strategy, step, state = _resnet50_train_setup(image, stem=stem)
    rng = np.random.default_rng(0)
    dev_batch = strategy.shard_batch(
        {
            "image": rng.normal(size=(batch, image, image, 3)).astype(
                np.float32
            ),
            "label": rng.integers(1000, size=(batch,)).astype(np.int32),
        }
    )
    log(f"stem={stem} batch={batch} compiling...")
    compiled = step.lower(state, dev_batch).compile()
    flops = compiled_flops(compiled)
    for _ in range(5):
        state, metrics = step(state, dev_batch)
    float(metrics["loss"])
    t = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, dev_batch)
    float(metrics["loss"])
    dt = (time.perf_counter() - t) / iters
    rate = batch / dt
    note = ""
    if flops:
        peak = peak_flops() or float("nan")
        note = (
            f" tflops={flops / dt / 1e12:.1f}"
            f" mfu={flops / dt / peak * 100:.1f}%"
        )
    log(f"stem={stem} batch={batch} {rate:.0f} img/s step={dt * 1e3:.1f}ms{note}")
    return rate, state, step, dev_batch


def main():
    global t0
    from pytorch_distributed_tpu.utils.benchlock import start_measurement

    # lock BEFORE the budget clock starts: queue time behind another
    # run is not this run's measurement time
    _lock, t0 = start_measurement()  # noqa: F841 — held for life
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, nargs="+",
                    default=[128, 256, 512])
    ap.add_argument("--stems", type=str, nargs="+",
                    default=["imagenet", "s2d"])
    ap.add_argument("--trace", type=str, default=None)
    args = ap.parse_args()

    ptd.enable_compilation_cache()
    ptd.init_process_group()
    log(f"platform={ptd.platform()} kind={jax.devices()[0].device_kind}")

    best = (0.0, None)
    for stem in args.stems:
        for b in args.batches:
            rate, state, step, dev_batch = bench_batch(b, stem=stem)
            if rate > best[0]:
                best = (rate, (stem, b, state, step, dev_batch))
    if best[1]:
        log(f"best: stem={best[1][0]} batch={best[1][1]} {best[0]:.0f} img/s")

    if args.trace and best[1]:
        stem, b, state, step, dev_batch = best[1]
        log(f"tracing stem={stem} batch={b} -> {args.trace}")
        with jax.profiler.trace(args.trace):
            for _ in range(10):
                state, metrics = step(state, dev_batch)
            float(metrics["loss"])
        log("trace written")


if __name__ == "__main__":
    main()
