"""Decompose the f32 feed path: which stage owns the 1131 img/s ceiling?

VERDICT r4 weak #3: the r4 normalize vectorization won its ~2x microbench
but moved solo e2e feed only 1097.7 -> 1131.1 img/s (+3%) — so normalize
was never the feed bottleneck, and nothing names what is. This script
times each stage of the exact bench.py `feed_only` path in isolation, at
the same shapes (src=256, crop=224, B=128, world=1):

  rng      — the per-batch crop/flip parameter draw (crc32 + PCG init)
  assemble — ImageBatchPipeline.__call__ (rng + native crop/flip/
             normalize pf_image_batch)
  put      — jax.device_put of a pre-assembled f32 batch + block (the
             77 MB/batch host->"device" copy on the CPU backend)
  loader   — the full DataLoader loop (sampler + prefetch threads +
             assemble + put), i.e. the bench's own number

Run it under the measurement lock (solo core) — it IS a measurement.
Prints a stage table and the implied bound: if loader ~= assemble + put,
the prefetch overlap is not overlapping (1 core: it can't), and the
bigger of the two names the ceiling.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

t0 = time.time()


def log(msg):
    print(f"[{time.time() - t0:6.1f}s] {msg}", flush=True)


def main():
    global t0
    from pytorch_distributed_tpu.utils.benchlock import start_measurement

    _lock, t0 = start_measurement()  # noqa: F841 — held for life

    import jax
    import numpy as np

    import pytorch_distributed_tpu as ptd
    from pytorch_distributed_tpu.data import ArrayDataset, DataLoader
    from pytorch_distributed_tpu.data.native_pipeline import (
        ImageBatchPipeline,
    )
    from pytorch_distributed_tpu.parallel import DataParallel

    ptd.enable_compilation_cache()
    ptd.init_process_group()
    log(f"platform={ptd.platform()} world={ptd.get_world_size()}")

    n_img, src, crop, B, steps = 256, 256, 224, 128, 10
    rng = np.random.default_rng(0)
    ds = ArrayDataset(
        image=rng.integers(0, 256, size=(n_img, src, src, 3), dtype=np.uint8),
        label=rng.integers(1000, size=(n_img,)).astype(np.int32),
    )
    # the f32 escape-hatch path (this script decomposes the HOST f32
    # ceiling; uint8 is the default ingest since the §3d flip)
    pipe = ImageBatchPipeline(crop, train=True, device_normalize=False)
    strategy = DataParallel()
    sharding = strategy.batch_sharding()

    idx = np.arange(B, dtype=np.int64)

    def timeit(fn, warmup=2, iters=steps):
        for _ in range(warmup):
            fn()
        t = time.perf_counter()
        for _ in range(iters):
            fn()
        return (time.perf_counter() - t) / iters

    # -- rng: the python-side param draw only
    import zlib

    def rng_only():
        r = np.random.default_rng([0, 0, zlib.crc32(idx.tobytes()), B])
        r.integers(0, src - crop + 1, size=B, dtype=np.int32)
        r.integers(0, src - crop + 1, size=B, dtype=np.int32)
        r.integers(0, 2, size=B, dtype=np.uint8)

    t_rng = timeit(rng_only)

    # -- assemble: the full fetch callable (rng + native pass)
    t_asm = timeit(lambda: pipe(ds, idx))

    # -- put: ship one pre-assembled f32 batch (NEW buffer each call —
    # reusing one would let jax short-circuit on a cached committed array)
    batch = pipe(ds, idx)
    img = batch["image"]

    def put_once():
        fresh = img.copy()  # forces a real host->device copy every call
        out = jax.device_put(fresh, sharding)
        out.block_until_ready()

    t_put = timeit(put_once)
    # the copy() itself, to subtract
    t_copy = timeit(lambda: img.copy())

    # -- u8 put for comparison (1/4 the bytes)
    pipe_u8 = ImageBatchPipeline(crop, train=True, device_normalize=True)
    batch_u8 = pipe_u8(ds, idx)
    img_u8 = batch_u8["image"]

    def put_u8():
        fresh = img_u8.copy()
        jax.device_put(fresh, sharding).block_until_ready()

    t_put_u8 = timeit(put_u8)

    # -- loader: the bench's own e2e feed loop
    loader = DataLoader(
        ds, B, shuffle=True, sharding=sharding, fetch=pipe, prefetch=4,
    )

    def one_epoch():
        n = 0
        for b in loader:
            jax.block_until_ready(b["image"])
            n += b["label"].shape[0]
        return n

    one_epoch()  # warm
    t = time.perf_counter()
    epochs = 5
    total = sum(one_epoch() for _ in range(epochs))
    t_loader_img = (time.perf_counter() - t) / total  # s per image

    mb = B * crop * crop * 3 * 4 / 1e6
    rows = [
        ("rng param draw", t_rng, B / t_rng),
        ("assemble (rng+native)", t_asm, B / t_asm),
        ("device_put f32 (net of copy)", t_put - t_copy,
         B / (t_put - t_copy)),
        # raw, not net-of-copy: the u8 put is so cheap (CPU backend can
        # alias the host buffer) that subtracting the copy estimate
        # goes negative — report what was measured
        ("device_put u8  (incl. copy)", t_put_u8, B / t_put_u8),
        ("loader e2e", t_loader_img * B, 1.0 / t_loader_img),
    ]
    log(f"shapes: src={src} crop={crop} B={B} ({mb:.1f} MB f32/batch)")
    for name, sec, imps in rows:
        log(f"  {name:<30} {sec * 1e3:8.2f} ms/batch  {imps:8.0f} img/s")
    ser = t_asm + (t_put - t_copy)
    log(
        f"  assemble+put serial bound       {ser * 1e3:8.2f} ms/batch  "
        f"{B / ser:8.0f} img/s"
    )
    ratio = (t_asm + t_put - t_copy) / (t_loader_img * B)
    log(
        f"(assemble+put)/loader = {ratio:.2f} "
        f"(>1 = loader beats the serial sum, cache warmth; "
        f"<1 = loader overhead on top of the stages)"
    )


if __name__ == "__main__":
    main()
