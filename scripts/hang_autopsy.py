"""Merge per-rank flight-recorder dumps into a hang verdict.

Input: a directory of ``flight-rank<r>.json`` dumps — what every
surviving process writes on a collective deadline, a transport poison,
an elastic view-commit timeout, or ``SIGTERM`` when
``PTD_FLIGHT_DUMP`` is armed (runtime/flightrec.py).  Output: one
verdict naming the failure class —

* ``missing_rank`` — a rank's log ends (or it left no dump) while a
  peer shows the next collective started: the classic dead/desynced
  victim,
* ``mismatch`` — same occurrence index, different op/shape across
  ranks: the PTD001 violation class, post-mortem,
* ``straggler`` — streams agree but one rank's start stamps trail its
  peers beyond the r6 clock-offset budget,
* ``inconclusive`` — none of the above holds; the detail line says
  what evidence was (and wasn't) there.

Alongside the verdict the report prints a per-rank evidence table at
the deciding occurrence index, and each rank's last completed record
(the "how far did everyone get" view).

Exit status: 0 when a verdict other than ``inconclusive`` was reached,
2 on ``inconclusive``, 1 on unusable input (no dumps, duplicate
ranks).  ``--json`` emits the verdict dict as one JSON line instead of
the human report — the form the chaos drill asserts on.

Torn ``.tmp`` orphans (writer SIGKILLed mid-dump) and unparseable
files are skipped with a warning; ``--strict`` turns them into hard
errors.  Two dumps claiming the same rank are always refused — a
verdict merged over ambiguous evidence would be worse than none.

Usage::

    python scripts/hang_autopsy.py DUMP_DIR [--json] [--strict]
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pytorch_distributed_tpu.runtime import flightrec  # noqa: E402


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("dump_dir", help="directory holding flight-rank*.json")
    p.add_argument("--json", action="store_true",
                   help="emit the verdict dict as one JSON line")
    p.add_argument("--strict", action="store_true",
                   help="hard-error on torn/invalid dumps instead of skipping")
    return p.parse_args(argv)


def _fmt_evidence(rows, out):
    header = ("rank", "seq", "kind", "op", "count", "state")
    table = [header] + [
        tuple("-" if r[k] is None else str(r[k]) for k in header)
        for r in rows
    ]
    widths = [max(len(row[i]) for row in table) for i in range(len(header))]
    for j, row in enumerate(table):
        print("  " + "  ".join(c.ljust(w) for c, w in zip(row, widths)),
              file=out)
        if j == 0:
            print("  " + "  ".join("-" * w for w in widths), file=out)


def report(dumps, verdict, out=None):
    out = out or sys.stdout
    print("== Hang autopsy ==", file=out)
    print(f"  dumps: {len(dumps)} rank(s): {sorted(dumps)}", file=out)
    for r in sorted(dumps):
        p = dumps[r]
        done = [rec for rec in p.get("records", ())
                if rec["state"] == "completed"]
        last = (f"seq={done[-1]['seq']} {done[-1]['kind']}/{done[-1]['op']} "
                f"group={done[-1]['group']}" if done
                else "no collective completed")
        print(f"    rank {r}: {len(p.get('records', []))} record(s), "
              f"last completed {last}  (dump reason: {p.get('reason')})",
              file=out)
    print(f"\n  verdict: {verdict['verdict']}", file=out)
    if verdict["victim_rank"] is not None:
        print(f"  victim:  rank {verdict['victim_rank']} at seq "
              f"{verdict['seq']} ({verdict['op']}, group "
              f"{verdict['group']})", file=out)
    print(f"  detail:  {verdict['detail']}", file=out)
    if verdict["evidence"]:
        print("\n  evidence (deciding occurrence, one row per rank):",
              file=out)
        _fmt_evidence(verdict["evidence"], out)


def main(argv=None):
    args = parse_args(argv)
    if not os.path.isdir(args.dump_dir):
        print(f"hang_autopsy: no such directory: {args.dump_dir}",
              file=sys.stderr)
        return 1
    try:
        dumps = flightrec.load_dumps(args.dump_dir, strict=args.strict)
    except ValueError as e:
        print(f"hang_autopsy: {e}", file=sys.stderr)
        return 1
    if not dumps:
        print(f"hang_autopsy: no flight-rank*.json dumps under "
              f"{args.dump_dir}", file=sys.stderr)
        return 1
    verdict = flightrec.autopsy(dumps)
    if args.json:
        print(json.dumps(verdict))
    else:
        report(dumps, verdict)
    return 0 if verdict["verdict"] != "inconclusive" else 2


if __name__ == "__main__":
    sys.exit(main())
