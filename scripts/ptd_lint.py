#!/usr/bin/env python
"""ptdlint CLI — machine-check the repo's distributed-correctness
invariants (rule catalog: docs/DESIGN.md §14).

Default sweep: ``pytorch_distributed_tpu/`` + ``scripts/`` +
``bench.py`` + ``tests/`` (minus the deliberately-violating
``tests/lint_fixtures/`` corpus) against the checked-in baseline. Exit
status is 0 only when there are zero non-baselined findings, zero
parse errors, AND zero stale baseline entries — the baseline may only
shrink, so removing the last instance of a grandfathered pattern
forces its entry out too.

    python scripts/ptd_lint.py                 # human output
    python scripts/ptd_lint.py --json          # machine output
    python scripts/ptd_lint.py recipes/        # explicit path subset
    python scripts/ptd_lint.py --rules PTD001  # rule subset
    python scripts/ptd_lint.py --metrics-path runs/x/metrics.jsonl
                                               # split="lint" JSONL record

Imports only the stdlib + the analysis package on the default path;
``--metrics-path`` additionally loads the MetricsWriter protocol (which
pulls the runtime, i.e. jax) so lint counts land in the same JSONL
stream every other subsystem reports through.

Suppression: ``# ptdlint: disable=PTD00N`` on (or directly above) the
flagged line. Baseline: ``ptdlint_baseline.json`` at the repo root —
``--write-baseline`` regenerates it from the current findings (every
entry then needs a real justification filled in before review).
"""

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from pytorch_distributed_tpu.analysis import (  # noqa: E402
    Analyzer,
    Baseline,
    BaselineEntry,
    default_rules,
)
from pytorch_distributed_tpu.analysis.core import (  # noqa: E402
    PARSE_ERROR_RULE,
)

DEFAULT_PATHS = ("pytorch_distributed_tpu", "scripts", "bench.py", "tests")
#: the fixtures corpus is deliberately full of violations
DEFAULT_EXCLUDE = ("tests/lint_fixtures",)
BASELINE_NAME = "ptdlint_baseline.json"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "paths", nargs="*", default=None,
        help=f"files/dirs to lint (default: {', '.join(DEFAULT_PATHS)})",
    )
    p.add_argument("--root", default=_ROOT, help="repo root")
    p.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: <root>/{BASELINE_NAME})",
    )
    p.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable findings on stdout",
    )
    p.add_argument(
        "--metrics-path", default=None,
        help="append one split='lint' record through MetricsWriter",
    )
    p.add_argument(
        "--write-baseline", action="store_true",
        help="regenerate the baseline from current findings (entries "
             "get a FILL-ME justification; shrink-only policy applies "
             "from then on)",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    rules = default_rules()
    if args.rules:
        wanted = {r.strip().upper() for r in args.rules.split(",")}
        unknown = wanted - {r.rule_id for r in rules}
        if unknown:
            print(f"unknown rule id(s): {sorted(unknown)}", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.rule_id in wanted]
    analyzer = Analyzer(args.root, rules, exclude=DEFAULT_EXCLUDE)
    paths = args.paths or list(DEFAULT_PATHS)
    findings = analyzer.run(paths)

    baseline_path = args.baseline or os.path.join(args.root, BASELINE_NAME)
    if args.write_baseline:
        if args.rules or args.paths:
            # a scoped run sees only a subset of findings; regenerating
            # from it would silently delete every out-of-scope entry
            # (and its hand-written justification)
            print(
                "--write-baseline only works on the full default sweep "
                "(no --rules, no explicit paths): a scoped regeneration "
                "would drop every out-of-scope entry",
                file=sys.stderr,
            )
            return 2
        entries = {
            f.fingerprint(): BaselineEntry(
                rule=f.rule_id, path=f.path, line_text=f.line_text,
                justification="FILL-ME: one-line justification",
            )
            for f in findings  # one entry per fingerprint: identical
            if f.rule_id != PARSE_ERROR_RULE  # never baselineable
        }                      # line texts in one file share it
        Baseline(list(entries.values())).save(baseline_path)
        print(
            f"wrote {len(entries)} entr{'y' if len(entries) == 1 else 'ies'}"
            f" to {baseline_path} — fill in every justification",
        )
        return 0
    baseline = Baseline.load(baseline_path)
    if args.rules:
        # a rule-subset run judges staleness only for entries its rules
        # could have matched; the rest are out of scope, not stale
        active = {r.rule_id for r in rules}
        baseline = Baseline(
            [e for e in baseline.entries if e.rule in active]
        )
    new, grandfathered, stale = baseline.apply(findings)
    parse_errors = [f for f in new if f.rule_id == PARSE_ERROR_RULE]

    counts: dict = {}
    for f in new:
        counts[f.rule_id] = counts.get(f.rule_id, 0) + 1
    ok = not new and not stale
    result = {
        "ok": ok,
        "paths": paths,
        "findings": [f.to_dict() for f in new],
        "baselined": [f.to_dict() for f in grandfathered],
        "stale_baseline": [
            {"rule": e.rule, "path": e.path, "line_text": e.line_text}
            for e in stale
        ],
        "counts": {
            "new": len(new),
            "baselined": len(grandfathered),
            "stale_baseline": len(stale),
            "parse_errors": len(parse_errors),
            **{f"rule.{k}": v for k, v in sorted(counts.items())},
        },
    }

    if args.metrics_path:
        _write_metrics(args.metrics_path, result)

    if args.as_json:
        json.dump(result, sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        for f in new:
            print(f"{f.path}:{f.line}: {f.rule_id} {f.message}")
        if stale:
            print(
                f"\n{len(stale)} stale baseline entr"
                f"{'y' if len(stale) == 1 else 'ies'} (shrink-only policy:"
                f" remove from {os.path.basename(baseline_path)}):"
            )
            for e in stale:
                print(f"  {e.rule} {e.path}: {e.line_text!r}")
        print(
            f"ptdlint: {len(new)} finding(s), "
            f"{len(grandfathered)} baselined, {len(stale)} stale "
            f"baseline entr{'y' if len(stale) == 1 else 'ies'}"
        )
    return 0 if ok else 1


def _write_metrics(path: str, result: dict) -> None:
    """One split='lint' JSONL record via the MetricsWriter protocol, so
    finding counts are trackable across PRs in the same stream every
    other subsystem reports through (lazy import: pulls the runtime)."""
    from pytorch_distributed_tpu.train.metrics import MetricsWriter

    with MetricsWriter(path) as w:
        w.write(
            0,
            {"event": "ptdlint", **result["counts"]},
            split="lint",
        )


if __name__ == "__main__":
    sys.exit(main())
