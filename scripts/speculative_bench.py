"""Speculative-decoding speedup on the real chip.

End-to-end serving demo of three round-3 features composing: distill a
small draft from the serving model ON-POLICY (distillation_loss_fn on
the target's own greedy continuations), then measure KV-cache decode
throughput plain vs speculative. Greedy speculation is output-identical
by construction, so the speedup number needs no quality asterisk — only
the workload caveat that random-init weights make degenerate (easy)
continuations, so the acceptance rate here is an upper-ish bound for
this model size.

Chip rules (docs/CHIP_PROTOCOL.md): run ON THE CHIP, no external kill
timers; budgets its own wall clock between phases via
PTD_PROBE_BUDGET_S (default 1800s).
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

t0 = time.time()
BUDGET_S = float(os.environ.get("PTD_PROBE_BUDGET_S", "1800"))


def log(msg):
    print(f"[{time.time() - t0:7.1f}s] {msg}", flush=True)


def over_budget():
    return time.time() - t0 > BUDGET_S


import jax
import jax.numpy as jnp
import numpy as np
import optax

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from pytorch_distributed_tpu.parallel import DataParallel
from pytorch_distributed_tpu.train import (
    TrainState,
    build_train_step,
    distillation_loss_fn,
)

B, P, NEW, K = 8, 64, 128, 4          # chip shapes (gpt2-small)
B_CPU, P_CPU, NEW_CPU, K_CPU = 4, 8, 8, 2  # smoke shapes (gpt2-tiny)
DISTILL_STEPS = 200


def main():
    global t0
    from pytorch_distributed_tpu.utils.benchlock import start_measurement

    # lock BEFORE the budget clock starts: queue time behind another
    # run is not this run's measurement time
    _lock, t0 = start_measurement()  # noqa: F841 — held for life
    global B, P, NEW, K
    ptd.enable_compilation_cache()
    ptd.init_process_group()
    on_tpu = ptd.is_tpu()
    log(f"platform={ptd.platform()}")
    if not on_tpu:
        # smoke: the speculative cache needs P + (NEW-1)(K+1) slots
        # within the tiny config's 64 positions
        B, P, NEW, K = B_CPU, P_CPU, NEW_CPU, K_CPU

    tcfg = GPT2Config.small() if on_tpu else GPT2Config.tiny()
    # the draft: ~10x fewer params, same vocab/positions
    dcfg = GPT2Config(
        vocab_size=tcfg.vocab_size, n_positions=tcfg.n_positions,
        hidden_size=max(tcfg.hidden_size // 4, 32),
        num_layers=2, num_heads=max(tcfg.num_heads // 4, 2),
        dropout_rate=0.0,
    )
    target, draft = GPT2LMHead(tcfg), GPT2LMHead(dcfg)
    rng = np.random.default_rng(0)
    ids0 = jnp.zeros((1, 16), jnp.int32)
    tp = target.init(jax.random.key(0), ids0)["params"]
    dp = draft.init(jax.random.key(1), ids0)["params"]
    prompts = jnp.asarray(
        rng.integers(tcfg.vocab_size, size=(B, P)).astype(np.int32)
    )

    # ---- baseline: plain greedy decode throughput -----------------------
    run_plain = jax.jit(lambda p, ids: ptd.generate(
        target, p, ids, max_new_tokens=NEW, temperature=0.0
    ))
    out = run_plain(tp, prompts); int(out[0, -1])
    iters = 5 if on_tpu else 2
    t = time.time()
    for _ in range(iters):
        out = run_plain(tp, prompts)
    int(out[0, -1])
    plain_dt = (time.time() - t) / iters
    plain_tok_s = B * NEW / plain_dt
    log(f"plain greedy: {plain_tok_s:9.0f} tok/s ({plain_dt*1e3:.0f} ms/call)")
    if over_budget():
        log("budget spent after baseline — stopping")
        return

    # ---- on-policy draft distillation -----------------------------------
    train_ids = ptd.generate(
        target, tp, prompts, max_new_tokens=NEW, temperature=0.0
    )
    strategy = DataParallel()
    state = strategy.place(TrainState.create(
        apply_fn=draft.apply, params=dp, tx=optax.adam(1e-3)
    ))
    step = strategy.compile(build_train_step(
        distillation_loss_fn(draft, target, tp, alpha=0.0, temperature=1.0)
    ), state)
    batch = strategy.shard_batch({"input_ids": np.asarray(train_ids)})
    kl = None
    for i in range(DISTILL_STEPS):
        state, m = step(state, batch)
        if i % 25 == 0:
            kl = float(m["kl"])  # sync bounds the dispatch chain too
            if over_budget():
                log(f"budget spent mid-distill at step {i}")
                break
    kl = float(m["kl"])
    dparams = jax.device_get(state.params)
    log(f"distilled {DISTILL_STEPS} steps, final kl={kl:.4f}")
    if over_budget():
        log("budget spent after distillation — skipping speculative phase")
        return

    # ---- speculative decode throughput ----------------------------------
    def spec(p, dpms, ids):
        return ptd.generate_speculative(
            target, p, draft, dpms, ids,
            max_new_tokens=NEW, num_draft_tokens=K,
        )

    run_spec = jax.jit(spec)
    out = run_spec(tp, dparams, prompts); int(out[0, -1])
    t = time.time()
    for _ in range(iters):
        out = run_spec(tp, dparams, prompts)
    int(out[0, -1])
    spec_dt = (time.time() - t) / iters
    spec_tok_s = B * NEW / spec_dt

    # outputs identical by construction — verify anyway (free honesty)
    same = bool((np.asarray(out) == np.asarray(run_plain(tp, prompts))).all())
    _, stats = ptd.generate_speculative(
        target, tp, draft, dparams, prompts,
        max_new_tokens=NEW, num_draft_tokens=K, return_stats=True,
    )
    acc = stats["accepted"] / max(stats["drafted"], 1)
    log(
        f"speculative: {spec_tok_s:9.0f} tok/s ({spec_dt*1e3:.0f} ms/call) "
        f"speedup={spec_tok_s/plain_tok_s:.2f}x acceptance={acc:.0%} "
        f"rounds={stats['rounds']} outputs_identical={same}"
    )
    print(
        f"RESULT speedup={spec_tok_s/plain_tok_s:.3f} "
        f"plain_tok_s={plain_tok_s:.0f} spec_tok_s={spec_tok_s:.0f} "
        f"acceptance={acc:.3f} identical={same}", flush=True,
    )


if __name__ == "__main__":
    main()
