#!/usr/bin/env python
"""Offered-load generator for the continuous-batching serve engine.

Drives ``serve.ServeEngine`` with a seeded stream of requests at a fixed
arrival rate (uniform or Poisson), streams SLO telemetry through the
MetricsWriter JSONL protocol, and prints the run summary — the
command-line twin of bench.py's ``serving`` phase, for interactive
profiling and capacity probing::

    python scripts/serve_loadgen.py --model gpt2-tiny --requests 32 \\
        --rate 30 --slots 8 --prompt-len 4,16 --new-tokens 8,32 \\
        --temperature 0.8 --top-p 0.95 --log /tmp/serve.jsonl

``--rate 0`` submits everything up front (closed-loop saturation).
Params are randomly initialized — the workload numbers (tokens/sec,
TTFT percentiles, occupancy) measure the ENGINE, not any checkpoint.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def build_model(name: str):
    if name == "gpt2-tiny":
        from pytorch_distributed_tpu.models.gpt2 import (
            GPT2Config, GPT2LMHead,
        )
        return GPT2LMHead(GPT2Config.tiny())
    if name == "gpt2-small":
        from pytorch_distributed_tpu.models.gpt2 import (
            GPT2Config, GPT2LMHead,
        )
        return GPT2LMHead(GPT2Config.small())
    if name == "llama-tiny":
        from pytorch_distributed_tpu.models.llama import (
            LlamaConfig, LlamaForCausalLM,
        )
        return LlamaForCausalLM(LlamaConfig.tiny())
    if name == "qwen2-tiny":
        from pytorch_distributed_tpu.models.qwen2 import (
            Qwen2Config, Qwen2ForCausalLM,
        )
        return Qwen2ForCausalLM(Qwen2Config.tiny())
    raise SystemExit(f"unknown --model {name!r}")


def parse_range(s: str):
    lo, _, hi = s.partition(",")
    lo = int(lo)
    return (lo, int(hi) if hi else lo)


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--model", default="gpt2-tiny",
                    help="gpt2-tiny | gpt2-small | llama-tiny | qwen2-tiny")
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--rate", type=float, default=0.0,
                    help="offered requests/sec (0 = submit all up front)")
    ap.add_argument("--poisson", action="store_true",
                    help="Poisson arrivals instead of uniform spacing")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--max-len", type=int, default=0,
                    help="per-slot KV capacity (0 = fit the workload)")
    ap.add_argument("--prefill-chunk", type=int, default=16)
    ap.add_argument("--prompt-len", type=parse_range, default=(4, 16),
                    metavar="LO[,HI]")
    ap.add_argument("--new-tokens", type=parse_range, default=(8, 32),
                    metavar="LO[,HI]")
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=None)
    ap.add_argument("--top-p", type=float, default=None)
    ap.add_argument("--deadline-s", type=float, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--log", default=None,
                    help="telemetry JSONL path (MetricsWriter stream)")
    args = ap.parse_args()

    import jax
    import numpy as np

    from pytorch_distributed_tpu.serve import (
        EngineConfig, Request, ServeEngine, ServeTelemetry, drive,
        uniform_arrivals, warm_up,
    )

    model = build_model(args.model)
    vocab = model.config.vocab_size
    rng = np.random.default_rng(args.seed)
    p_lo, p_hi = args.prompt_len
    n_lo, n_hi = args.new_tokens
    reqs = [
        Request(
            prompt_ids=rng.integers(
                1, vocab, size=rng.integers(p_lo, p_hi + 1)
            ).astype(np.int32),
            max_new_tokens=int(rng.integers(n_lo, n_hi + 1)),
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, deadline_s=args.deadline_s,
            seed=int(rng.integers(0, 2**31)),
        )
        for _ in range(args.requests)
    ]
    if args.rate > 0 and args.poisson:
        gaps = rng.exponential(1.0 / args.rate, size=args.requests)
        arrivals = list(np.cumsum(gaps) - gaps[0])
    else:
        arrivals = uniform_arrivals(args.requests, args.rate)

    # auto max_len fits the workload AND the shared warm-up (a 1-token
    # prompt rounds to one chunk + the 2 tokens that force the decode
    # compile); an EXPLICIT --max-len is never silently rewritten — if
    # it can't hold the warm-up, warm_up's submit fails loudly
    max_len = args.max_len or max(
        [
            -(-r.prompt_len // args.prefill_chunk) * args.prefill_chunk
            + r.max_new_tokens
            for r in reqs
        ] + [args.prefill_chunk + 2]
    )
    writer = None
    if args.log:
        from pytorch_distributed_tpu.train.metrics import MetricsWriter
        writer = MetricsWriter(args.log)

    import jax.numpy as jnp  # noqa: F401 — backend init before timing

    params = model.init(
        jax.random.key(0),
        np.zeros((1, min(8, max_len - 1)), np.int32),
    )["params"]
    engine = ServeEngine(
        model, params,
        EngineConfig(num_slots=args.slots, max_len=max_len,
                     prefill_chunk=args.prefill_chunk),
    )
    # serve.loadgen's shared warm-up/pacing: both programs compile
    # outside the measured window, the JSONL stream starts clean, and
    # the pacing matches bench.py's serving phase exactly
    warm_up(engine, np.ones(1, np.int32),
            telemetry=ServeTelemetry(writer=writer))
    dt = drive(engine, reqs, arrivals)

    if writer is not None:
        writer.close()
    s = engine.telemetry.summary()
    print(f"model={args.model} slots={args.slots} max_len={max_len} "
          f"requests={args.requests} rate="
          f"{args.rate or 'closed-loop'} wall={dt:.2f}s")
    for k in sorted(s):
        v = s[k]
        print(f"  {k:>18} = {v:.2f}" if isinstance(v, float)
              else f"  {k:>18} = {v}")
    print(f"  decode compiles    = {engine.decode_compiles} "
          f"(static-shape invariant: must be 1)")
    if args.log:
        print(f"telemetry JSONL -> {args.log}")


if __name__ == "__main__":
    main()
