#!/usr/bin/env python
"""Offered-load generator for the continuous-batching serve engine.

Drives ``serve.ServeEngine`` with a seeded stream of requests at a fixed
arrival rate (uniform or Poisson), streams SLO telemetry through the
MetricsWriter JSONL protocol, and prints the run summary — the
command-line twin of bench.py's ``serving`` phase, for interactive
profiling and capacity probing::

    python scripts/serve_loadgen.py --model gpt2-tiny --requests 32 \\
        --rate 30 --slots 8 --prompt-len 4,16 --new-tokens 8,32 \\
        --temperature 0.8 --top-p 0.95 --log /tmp/serve.jsonl

``--rate 0`` submits everything up front (closed-loop saturation).
Params are randomly initialized — the workload numbers (tokens/sec,
TTFT percentiles, occupancy) measure the ENGINE, not any checkpoint.

Storm mode (r18) drives a FLEET behind the deterministic router::

    python scripts/serve_loadgen.py --engines 4 --router --requests 64
    python scripts/serve_loadgen.py --engines 4 --router --disagg \\
        --store --prefix-share 0.8 --requests 64

``--router`` load-balances N solo engines; ``--disagg`` splits them
into prefill/decode tiers with ring KV migration between them;
``--store`` shares one cross-engine prefix registry so a hot system
prompt is prefilled once per fleet. Arrivals stay seeded and
replayable — the same ``--seed`` routes the same storm identically —
and the summary reports p50/p95/p99 TTFT, aggregate tokens/s,
migration bytes, and replay counts.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def build_model(name: str):
    if name == "gpt2-tiny":
        from pytorch_distributed_tpu.models.gpt2 import (
            GPT2Config, GPT2LMHead,
        )
        return GPT2LMHead(GPT2Config.tiny())
    if name == "gpt2-small":
        from pytorch_distributed_tpu.models.gpt2 import (
            GPT2Config, GPT2LMHead,
        )
        return GPT2LMHead(GPT2Config.small())
    if name == "llama-tiny":
        from pytorch_distributed_tpu.models.llama import (
            LlamaConfig, LlamaForCausalLM,
        )
        return LlamaForCausalLM(LlamaConfig.tiny())
    if name == "qwen2-tiny":
        from pytorch_distributed_tpu.models.qwen2 import (
            Qwen2Config, Qwen2ForCausalLM,
        )
        return Qwen2ForCausalLM(Qwen2Config.tiny())
    raise SystemExit(f"unknown --model {name!r}")


def parse_range(s: str):
    lo, _, hi = s.partition(",")
    lo = int(lo)
    return (lo, int(hi) if hi else lo)


def run_storm(args, model, params, max_len, reqs, arrivals, writer,
              spec):
    """--router fleet storm: N solo engines, or --disagg tiers with
    ring KV migration, behind the deterministic router."""
    import numpy as np

    from pytorch_distributed_tpu.serve import (
        EngineConfig, InProcPrefixStore, Router, ServeEngine, drive,
    )

    store = InProcPrefixStore() if args.store else None

    def mk(role, eid):
        return ServeEngine(
            model, params,
            EngineConfig(num_slots=args.slots, max_len=max_len,
                         prefill_chunk=args.prefill_chunk,
                         page_size=args.page_size,
                         num_pages=args.num_pages,
                         decode_mode=args.decode_mode,
                         role=role, engine_id=eid),
            spec=spec if role == "solo" else None,
            prefix_store=store if role != "decode" else None,
            telemetry=None,
        )

    if args.disagg:
        n_pre = -(-args.engines // 2)
        prefill = [mk("prefill", f"p{i}") for i in range(n_pre)]
        decode = [
            mk("decode", f"d{i}") for i in range(args.engines - n_pre)
        ]
        for e in prefill + decode:
            e.telemetry.writer = writer
        router = Router(prefill=prefill, decode=decode, writer=writer,
                        store=store)
        shape = f"{n_pre} prefill + {args.engines - n_pre} decode"
    else:
        engines = [mk("solo", f"e{i}") for i in range(args.engines)]
        for e in engines:
            e.telemetry.writer = writer
        router = Router(engines=engines, writer=writer, store=store)
        shape = f"{args.engines} solo"
    router.warm_up(np.ones(1, np.int32))
    dt = drive(router, reqs, arrivals)
    if writer is not None:
        writer.close()
    s = router.summary()
    total_tokens = sum(
        e["completed_tokens"] for e in s["engines"].values()
    )
    print(f"model={args.model} fleet=[{shape}] max_len={max_len} "
          f"requests={args.requests} rate="
          f"{args.rate or 'closed-loop'} wall={dt:.2f}s")
    print(f"  tokens/s (fleet)   = {total_tokens / max(dt, 1e-9):.2f} "
          f"({total_tokens} completed tokens)")
    for q in (50, 95, 99):
        v = s.get(f"ttft_ms_p{q}")
        if v is not None:
            print(f"  ttft_ms_p{q:<8} = {v:.2f}")
    if args.disagg:
        print(f"  migration          = {s['migration_frames']} frames, "
              f"{s['migration_bytes']:,d} wire B "
              f"({s['migration_payload_bytes']:,d} KV payload B)")
    if s["replays"] or s["lost_engines"]:
        print(f"  replays            = {s['replays']} "
              f"(lost engines: {s['lost_engines']})")
    if store is not None:
        st = store.stats()
        print(f"  prefix store       = {st['puts']} puts "
              f"({st['hits']} hits, {st['dup_puts']} dup puts, "
              f"{st['entries']} resident pages)")
    for eid, es in s["engines"].items():
        done = es.get("completed", 0)
        print(f"  [{eid}] completed={done} "
              f"tokens={es['completed_tokens']} "
              + (f"p99={es['ttft_ms_p99']:.1f}ms"
                 if "ttft_ms_p99" in es else ""))
    if args.log:
        print(f"telemetry JSONL -> {args.log}")


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--model", default="gpt2-tiny",
                    help="gpt2-tiny | gpt2-small | llama-tiny | qwen2-tiny")
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--rate", type=float, default=0.0,
                    help="offered requests/sec (0 = submit all up front)")
    ap.add_argument("--poisson", action="store_true",
                    help="Poisson arrivals instead of uniform spacing")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--max-len", type=int, default=0,
                    help="per-slot KV capacity (0 = fit the workload)")
    ap.add_argument("--prefill-chunk", type=int, default=16)
    ap.add_argument("--prompt-len", type=parse_range, default=(4, 16),
                    metavar="LO[,HI]")
    ap.add_argument("--new-tokens", type=parse_range, default=(8, 32),
                    metavar="LO[,HI]")
    ap.add_argument("--prefix-share", type=float, default=0.0,
                    help="fraction of requests opening with one common "
                    "system prompt (exercises the paged pool's "
                    "copy-free prefix sharing)")
    ap.add_argument("--prefix-len", type=int, default=16,
                    help="length of the shared system prompt")
    ap.add_argument("--page-size", type=int, default=None,
                    help="KV page size (default: auto divisor of max_len)")
    ap.add_argument("--num-pages", type=int, default=None,
                    help="KV pool pages (default: parity with the old "
                    "fixed [slots, max_len] pool)")
    ap.add_argument("--long-context", action="store_true",
                    help="preset: size max_len WELL past the live "
                    "lengths (4x the workload fit, >= 256, capped at "
                    "the model's position table) — the regime paged "
                    "attention exists for; the summary's bytes/token "
                    "shows the decode path streaming the live bucket "
                    "instead of the max_len-wide gather")
    ap.add_argument("--decode-mode", choices=("paged", "dense"),
                    default="paged",
                    help="'dense' runs the round-11 full-width gather "
                    "tick (the A/B baseline) instead of paged attention")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="enable speculative decoding with k draft "
                    "tokens per tick (draft = a randomly initialized "
                    "1-layer sibling — measures ENGINE mechanics, the "
                    "acceptance rate of a real trained draft will "
                    "differ)")
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=None)
    ap.add_argument("--top-p", type=float, default=None)
    ap.add_argument("--deadline-s", type=float, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--log", default=None,
                    help="telemetry JSONL path (MetricsWriter stream)")
    ap.add_argument("--engines", type=int, default=1,
                    help="fleet size for --router storm mode")
    ap.add_argument("--router", action="store_true",
                    help="drive --engines N engines behind the "
                    "deterministic telemetry-driven router")
    ap.add_argument("--disagg", action="store_true",
                    help="split the fleet into prefill/decode tiers "
                    "(half each, prefill rounded up) with ring KV "
                    "migration between them; implies --router")
    ap.add_argument("--store", action="store_true",
                    help="share one cross-engine prefix store across "
                    "the fleet (hot prompts prefilled once per fleet)")
    args = ap.parse_args()
    if args.disagg:
        args.router = True
    if args.router and args.engines < 2:
        ap.error("--router needs --engines >= 2 (a 1-engine fleet is "
                 "just the solo path — drop --router)")
    if args.disagg and args.spec_k:
        ap.error("--disagg refuses --spec-k: tiered speculation is not "
                 "supported (the draft cache does not ride the "
                 "migration frame)")
    if args.store and not args.router:
        ap.error("--store is a FLEET feature (cross-engine registry) — "
                 "a single engine already has its local page registry; "
                 "add --router --engines N")
    if args.long_context and args.max_len:
        # the preset's whole job is sizing max_len; honoring both would
        # either silently drop the preset or silently rewrite an
        # explicit --max-len — refused, like every contradictory-flag
        # combination in this repo
        ap.error("--long-context sizes max_len itself — pass one of "
                 "--long-context / --max-len, not both")

    import jax
    import numpy as np

    from pytorch_distributed_tpu.serve import (
        EngineConfig, ServeEngine, ServeTelemetry, SpecConfig, drive,
        prefix_shared_requests, uniform_arrivals, warm_up,
    )

    model = build_model(args.model)
    vocab = model.config.vocab_size
    rng = np.random.default_rng(args.seed)
    reqs = prefix_shared_requests(
        rng, args.requests, vocab,
        prompt_len=args.prompt_len, new_tokens=args.new_tokens,
        prefix_share=args.prefix_share,
        shared_prefix_len=args.prefix_len if args.prefix_share else 0,
        temperature=args.temperature, top_k=args.top_k,
        top_p=args.top_p, deadline_s=args.deadline_s,
    )
    if args.rate > 0 and args.poisson:
        gaps = rng.exponential(1.0 / args.rate, size=args.requests)
        arrivals = list(np.cumsum(gaps) - gaps[0])
    else:
        arrivals = uniform_arrivals(args.requests, args.rate)

    # auto max_len fits the workload AND the shared warm-up (a 1-token
    # prompt rounds to one chunk + the 2 tokens that force the decode
    # compile); an EXPLICIT --max-len is never silently rewritten — if
    # it can't hold the warm-up, warm_up's submit fails loudly
    max_len = args.max_len or max(
        [
            -(-r.prompt_len // args.prefill_chunk) * args.prefill_chunk
            + r.max_new_tokens + args.spec_k
            for r in reqs
        ] + [args.prefill_chunk + 2 + args.spec_k]
    )
    if args.long_context and not args.max_len:
        # the long-context mix: a pool sized far past the live lengths
        # (capped at the model's position table) so the decode tick's
        # bucketed stream, not max_len, sets the bytes/token
        from pytorch_distributed_tpu.generation import model_max_len

        limit = model_max_len(model) or 1 << 30
        max_len = min(max(4 * max_len, 256), limit)
        if args.page_size:
            # align DOWN while still at the cap — the generic round-UP
            # below must never push a limit-capped max_len past the
            # model's position table (engine construction would refuse)
            max_len = max(
                max_len - max_len % args.page_size, args.page_size
            )
    if not args.max_len and args.page_size:
        # only the AUTO-computed fit is rounded up to a page multiple;
        # an explicit --max-len is never silently rewritten — if it
        # doesn't divide by --page-size, EngineConfig refuses loudly
        max_len = -(-max_len // args.page_size) * args.page_size
    writer = None
    if args.log:
        from pytorch_distributed_tpu.train.metrics import MetricsWriter
        writer = MetricsWriter(args.log)

    import jax.numpy as jnp  # noqa: F401 — backend init before timing

    params = model.init(
        jax.random.key(0),
        np.zeros((1, min(8, max_len - 1)), np.int32),
    )["params"]
    spec = None
    if args.spec_k:
        import dataclasses as _dc

        dcfg = _dc.replace(
            model.config, num_layers=1,
            hidden_size=max(model.config.hidden_size // 2, 16),
        )
        draft = type(model)(dcfg)
        dparams = draft.init(
            jax.random.key(1),
            np.zeros((1, min(8, max_len - 1)), np.int32),
        )["params"]
        spec = SpecConfig(draft, dparams,
                          num_draft_tokens=args.spec_k)
    if args.router:
        run_storm(args, model, params, max_len, reqs, arrivals,
                  writer, spec)
        return
    engine = ServeEngine(
        model, params,
        EngineConfig(num_slots=args.slots, max_len=max_len,
                     prefill_chunk=args.prefill_chunk,
                     page_size=args.page_size,
                     num_pages=args.num_pages,
                     decode_mode=args.decode_mode),
        spec=spec,
    )
    # serve.loadgen's shared warm-up/pacing: both programs compile
    # outside the measured window, the JSONL stream starts clean, and
    # the pacing matches bench.py's serving phase exactly
    warm_up(engine, np.ones(1, np.int32),
            telemetry=ServeTelemetry(writer=writer))
    dt = drive(engine, reqs, arrivals)

    if writer is not None:
        writer.close()
    s = engine.telemetry.summary()
    print(f"model={args.model} slots={args.slots} max_len={max_len} "
          f"requests={args.requests} rate="
          f"{args.rate or 'closed-loop'} wall={dt:.2f}s")
    for k in sorted(s):
        v = s[k]
        print(f"  {k:>18} = {v:.2f}" if isinstance(v, float)
              else f"  {k:>18} = {v}")
    pool = engine.pool
    print(f"  decode compiles    = {engine.decode_compiles} "
          f"(bounded-compile invariant: one per occupied length "
          f"bucket, buckets={sorted(engine.decode_buckets)} pages)")
    print(f"  kv pages           = {pool.peak_pages} peak / "
          f"{pool.num_pages} total (page_size={pool.page_size})")
    print(f"  prefix hit rate    = {pool.prefix_hit_rate:.3f} "
          f"({pool.prefix_hits}/{pool.prefix_lookups} admissions, "
          f"{pool.shared_tokens} prompt tokens served copy-free)")
    print(f"  decode bytes/token = "
          f"{engine.decode_hbm_bytes_per_token:,.0f} analytic HBM "
          f"(mode={args.decode_mode}, gather "
          f"{engine.decode_gather_bytes:,d} B total — the dense-"
          f"intermediate tax paged attention removes)")
    if engine.spec is not None and engine.spec_verifies:
        print(f"  spec accept/verify = "
              f"{engine.spec_accepted / engine.spec_verifies:.2f} "
              f"(k={engine.spec.num_draft_tokens}, "
              f"{engine.spec_verifies} verifies, "
              f"{engine.spec_accepted}/{engine.spec_drafted} drafts "
              f"accepted)")
    if args.log:
        print(f"telemetry JSONL -> {args.log}")


if __name__ == "__main__":
    main()
