#!/bin/bash
# Persistent chip-evidence capture loop (docs/CHIP_PROTOCOL.md rules:
# one relay client at a time, NO external kill timers, capture order
# cheap-first). Run detached at session start:
#
#   setsid scripts/chip_capture_loop.sh < /dev/null > /dev/null 2>&1 &
#
# Cycles bench.py (internal budgets; CPU-fallback is harmless and keeps
# the driver-contract path exercised) until a platform:tpu capture
# lands, then runs the post-capture chain once and exits. Poll
# chip_evidence/capture_loop.log; artifacts land in chip_evidence/ for
# committing as they appear.
cd "$(dirname "$0")/.."
EV=chip_evidence
# unique per loop START: a restarted loop must never reuse an earlier
# run's attempt numbering and truncate committed evidence files
TAG=${1:-loop}_$(date -u +%d%H%M)
log() { echo "[$TAG $(date -u +%H:%M:%S)] $*" >> $EV/capture_loop.log; }

log "=== capture loop start ==="
attempt=0
while true; do
  attempt=$((attempt+1))
  log "attempt $attempt: bench.py"
  PTD_BENCH_BUDGET_S=4200 python bench.py \
    > $EV/bench_${TAG}_$attempt.out 2> $EV/bench_${TAG}_$attempt.err
  log "attempt $attempt bench rc=$?"
  if grep -q '"platform": "tpu"' $EV/bench_${TAG}_$attempt.out; then
    log "TPU capture landed — running the post-capture chain"
    python scripts/gpt2_variants.py > $EV/gpt2_variants_${TAG}.log 2>&1
    log "variants rc=$?"
    # the first-ever executed 8B step (VERDICT r3 #2) — early in the
    # chain: if the relay dies mid-chain this is the evidence to have
    PTD_PROBE_BUDGET_S=2400 python scripts/llama8b_decode.py \
      > $EV/llama8b_decode_${TAG}.log 2>&1
    log "llama8b rc=$?"
    python scripts/accuracy_proxy.py > $EV/accuracy_proxy_${TAG}.log 2>&1
    log "accuracy rc=$?"
    python scripts/resnet_sweep.py --stems imagenet s2d \
      > $EV/resnet_sweep_${TAG}.log 2>&1
    log "sweep rc=$?"
    PTD_PROBE_BUDGET_S=1500 python scripts/speculative_bench.py \
      > $EV/speculative_bench_${TAG}.log 2>&1
    log "speculative rc=$?"
    # experimental kernels LAST (the documented relay-wedge hazard)
    PTD_PROBE_BUDGET_S=1200 python scripts/flash_compile_diag.py \
      > $EV/flash_diag_${TAG}.log 2>&1
    log "flash diag rc=$?"
    PTD_PROBE_BUDGET_S=1200 python scripts/flash_vs_xla.py \
      > $EV/flash_vs_xla_${TAG}.log 2>&1
    log "flash vs xla rc=$?"
    log "=== chain complete ==="
    break
  fi
  # a failed probe already burned its internal retry; short gap, retry.
  # Prune the repetitive fallback logs so the evidence dir stays legible
  # (keep attempt 1 and the latest).
  if [ "$attempt" -gt 2 ]; then
    prev=$((attempt-1))
    [ "$prev" -gt 1 ] && rm -f $EV/bench_${TAG}_$prev.out $EV/bench_${TAG}_$prev.err
  fi
  sleep 300
done
log "=== capture loop exit ==="
