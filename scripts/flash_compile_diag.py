"""Diagnose the flash-attention compile blowup (VERDICT r2 ask #5).

Round 2 observed >9 min cold compiles at the bench shape (8,1024,16,64)
while the probe shapes compiled in 2-7 s — with no evidence whether the
cost scales with the GRID (program count: B*H * S/bq * S/bk), the BLOCK
(Mosaic per-kernel work / vmem pressure), the BATCH, or is mostly
remote-compile RTT. This script separates the axes:

* ``jit(...).lower()``   — local tracing + Pallas lowering (no relay)
* ``lowered.compile()``  — the remote XLA+Mosaic backend compile

and walks one axis at a time from a baseline (1,512,4,64) bq=bk=128:
sequence only, batch*heads only, block only, then fwd+bwd at the winner.
The persistent compile cache is deliberately NOT enabled, so every
compile in the sweep is cold.

Chip protocol: internal budget (PTD_PROBE_BUDGET_S, default 1200 s),
checked BETWEEN compiles; never kill this process externally
(docs/CHIP_PROTOCOL.md).
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

t0 = time.time()
BUDGET_S = float(os.environ.get("PTD_PROBE_BUDGET_S", "1200"))


def log(msg):
    print(f"[{time.time() - t0:8.1f}s] {msg}", flush=True)


def over_budget():
    return time.time() - t0 > BUDGET_S


import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.ops.flash_attention import flash_attention

# deliberately NOT enabling the persistent cache: cold numbers only

CASES = [
    # label, (B, S, H, D), block
    ("base           ", (1, 512, 4, 64), 128),
    ("seq 2x         ", (1, 1024, 4, 64), 128),
    ("seq 4x         ", (1, 2048, 4, 64), 128),
    ("batch*heads 8x ", (8, 512, 4, 64), 128),
    ("heads 4x       ", (1, 512, 16, 64), 128),
    ("block 256      ", (1, 512, 4, 64), 256),
    ("block 512      ", (1, 1024, 4, 64), 512),
    ("bench shape    ", (8, 1024, 16, 64), 128),
    ("bench blk 256  ", (8, 1024, 16, 64), 256),
]


def main():
    global t0
    from pytorch_distributed_tpu.utils.benchlock import start_measurement

    # lock BEFORE the budget clock starts: queue time behind another
    # run is not this run's measurement time
    _lock, t0 = start_measurement()  # noqa: F841 — held for life
    log(f"platform={jax.devices()[0].platform} "
        f"kind={jax.devices()[0].device_kind}")
    results = []
    for label, (B, S, H, D), blk in CASES:
        if over_budget():
            log(f"budget spent — skipping from {label!r} on")
            break
        rng = np.random.default_rng(0)
        q = jnp.asarray(
            rng.normal(size=(B, S, H, D)).astype(np.float32)
        ).astype(jnp.bfloat16)

        def fn(q, k, v):
            return flash_attention(q, k, v, causal=True,
                                   block_q=blk, block_k=blk)

        t = time.time()
        lowered = jax.jit(fn).lower(q, q, q)
        lower_s = time.time() - t
        t = time.time()
        compiled = lowered.compile()
        compile_s = time.time() - t
        grid = B * H * (S // min(blk, S)) ** 2
        log(f"{label} B{B} S{S} H{H} blk{blk} grid={grid:6d} "
            f"lower={lower_s:6.2f}s compile={compile_s:7.2f}s")
        results.append((label.strip(), grid, lower_s, compile_s))
        del compiled

    # fwd+bwd at the bench shape only if the budget survived the sweep
    if not over_budget():
        B, S, H, D = 8, 1024, 16, 64
        rng = np.random.default_rng(0)
        q = jnp.asarray(
            rng.normal(size=(B, S, H, D)).astype(np.float32)
        ).astype(jnp.bfloat16)

        def loss(q, k, v):
            return flash_attention(q, k, v, causal=True).astype(
                jnp.float32).sum()

        t = time.time()
        lowered = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(q, q, q)
        lower_s = time.time() - t
        t = time.time()
        lowered.compile()
        compile_s = time.time() - t
        log(f"bench fwd+bwd   lower={lower_s:6.2f}s "
            f"compile={compile_s:7.2f}s")

    log("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
