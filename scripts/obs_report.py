"""Render a run directory into a human-readable observability report.

Input: what one ``--trace-dir`` / ``TrainerConfig.trace`` run leaves
behind — a Chrome-format ``trace.json`` (runtime/tracing.py) and/or any
MetricsWriter JSONL streams (step records with ``goodput_pct``,
``split="trace"`` span rollups, ``split="goodput"`` accounts,
``split="serve"`` telemetry). Output: the tables a slow-step
investigation starts from —

* step-phase breakdown: per-span count / total / mean / p50 / p95 /
  p99 / max and share of traced wall time,
* top-N widest individual spans (the outliers percentiles hide),
* recompile sentinel summary (anything after warm-up is a finding),
* goodput summary (productive / stalled / recovering / checkpoint /
  other seconds; buckets sum to wall),
* comms: per-op calls / wire bytes / wall and achieved GB/s from the
  ``comm.*`` spans (runtime/hostring.py), predicted-vs-achieved
  latency when a calibrated ``costmodel.json`` sits in the run dir,
  and per-rank straggler skew when the trace is a
  ``scripts/trace_merge.py`` merge of several ranks,
* stragglers: per-rank step-time skew when the trace is a
  ``scripts/trace_merge.py`` merge (its k-th-occurrence alignment
  puts every rank's k-th step on one clock), the ``train.rank_skew``
  gauge the elastic balancer emits at each rebalance boundary, and
  the rebalance audit trail (``split="elastic"`` records: per-rank
  shard counts, measured skew, whether ownership moved),
* checkpoint: the ``split="ckpt"`` audit trail — every save's
  format/tag/world/replication and per-rank vs total bytes, every
  restore's adopted tag with its peer-fetch / walk-back / stranded-
  write counts — plus per-rank ``elastic.checkpoint`` save walls from
  a merged trace (sharded saves should be balanced; the full format
  concentrates the write on rank 0),
* plan: the auto-parallel planner's ranked candidate table when a
  ``plan.json`` (``--strategy auto`` / autoplan/planner.py) sits in
  the run dir — the audit trail for why this run's strategy was
  chosen,
* hang autopsy: when the run dir holds ``flight-rank*.json`` dumps
  (what every surviving rank's always-on flight recorder writes on a
  collective deadline or transport poison, runtime/flightrec.py), the
  merged verdict — missing_rank / mismatch / straggler — with the
  per-rank evidence rows at the deciding occurrence,
* serving: TTFT percentiles plus the paged-KV saturation picture from
  ``split="serve"`` snapshots — peak pages in use, prefix-cache hit
  rate, and speculative accepted-tokens-per-verify when the engine ran
  with ``SpecConfig``.

Usage::

    python scripts/obs_report.py RUN_DIR [--top 10]
    python scripts/obs_report.py --trace trace.json --metrics m.jsonl

Works with either input alone: a chaos-drill dir usually has only the
JSONL (rollups + goodput), a bench dir maybe only the trace. In a run
dir with no ``trace.json``, a ``merged_trace.json`` (trace_merge
output) is picked up instead.
"""

import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pytorch_distributed_tpu.runtime.tracing import summarize_goodput  # noqa: E402
from pytorch_distributed_tpu.train.metrics import read_metrics  # noqa: E402
from pytorch_distributed_tpu.utils.timing import percentile  # noqa: E402


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("run_dir", nargs="?", default=None,
                   help="directory holding trace.json and/or *.jsonl")
    p.add_argument("--trace", default=None, help="explicit trace.json path")
    p.add_argument("--metrics", action="append", default=None,
                   help="explicit metrics JSONL path (repeatable)")
    p.add_argument("--top", type=int, default=10,
                   help="how many widest spans to list")
    p.add_argument("--costmodel", default=None,
                   help="calibrated costmodel.json for the "
                   "achieved-vs-predicted comms comparison (default: "
                   "<run_dir>/costmodel.json when present)")
    p.add_argument("--plan", default=None,
                   help="auto-parallel plan.json to render (default: "
                   "<run_dir>/plan.json when present)")
    return p.parse_args(argv)


def _discover(args):
    trace_path, metric_paths = args.trace, list(args.metrics or [])
    costmodel_path, plan_path = args.costmodel, args.plan
    flight_dir = None
    if args.run_dir:
        if glob.glob(os.path.join(args.run_dir, "flight-rank*.json")):
            flight_dir = args.run_dir
        if trace_path is None:
            for name in ("trace.json", "merged_trace.json"):
                cand = os.path.join(args.run_dir, name)
                if os.path.isfile(cand):
                    trace_path = cand
                    break
        if not metric_paths:
            metric_paths = sorted(
                glob.glob(os.path.join(args.run_dir, "*.jsonl"))
            )
        if costmodel_path is None:
            cand = os.path.join(args.run_dir, "costmodel.json")
            costmodel_path = cand if os.path.isfile(cand) else None
        if plan_path is None:
            cand = os.path.join(args.run_dir, "plan.json")
            plan_path = cand if os.path.isfile(cand) else None
    return trace_path, metric_paths, costmodel_path, plan_path, flight_dir


def plan_section(plan_path, out):
    """Render the auto-parallel planner's ranked candidate table."""
    if not plan_path:
        return None
    from pytorch_distributed_tpu.autoplan.planner import format_plan

    try:
        with open(plan_path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"\n== Plan ==\n  (plan {plan_path} unreadable: {e})",
              file=out)
        return None
    print("\n== Plan ==", file=out)
    print(f"  source: {plan_path}", file=out)
    try:
        lines = format_plan(doc)
    except (KeyError, TypeError, AttributeError) as e:
        # a truncated/hand-edited/future-format plan must degrade to a
        # note, not abort the report's remaining sections (same
        # convention as an unreadable costmodel.json above)
        print(f"  (plan {plan_path} does not match the expected "
              f"schema: {type(e).__name__}: {e})", file=out)
        return None
    for line in lines:
        print("  " + line, file=out)
    return doc


def hang_section(flight_dir, out):
    """Render the flight-recorder hang autopsy when a run dir holds
    ``flight-rank*.json`` dumps — what every surviving rank writes on a
    collective deadline, a transport poison, or an elastic view-commit
    timeout (runtime/flightrec.py)."""
    if not flight_dir:
        return None
    from pytorch_distributed_tpu.runtime import flightrec

    try:
        dumps = flightrec.load_dumps(flight_dir)
    except ValueError as e:
        print(f"\n== Hang autopsy ==\n  (flight dumps unusable: {e})",
              file=out)
        return None
    if not dumps:
        return None
    verdict = flightrec.autopsy(dumps)
    print("\n== Hang autopsy ==", file=out)
    print(f"  source: {len(dumps)} flight dump(s) under {flight_dir} "
          f"(ranks {sorted(dumps)})", file=out)
    print(f"  verdict: {verdict['verdict']}", file=out)
    if verdict["victim_rank"] is not None:
        print(f"  victim:  rank {verdict['victim_rank']} at seq "
              f"{verdict['seq']} ({verdict['op']}, group "
              f"{verdict['group']})", file=out)
    print(f"  detail:  {verdict['detail']}", file=out)
    for r in verdict["evidence"]:
        state = r["state"]
        desc = ("left no dump" if state == "absent" else
                f"seq={r['seq']} {r['kind']}/{r['op']} "
                f"count={r['count']} [{state}]")
        print(f"    rank {r['rank']}: {desc}", file=out)
    print("  (full per-rank report: python scripts/hang_autopsy.py "
          f"{flight_dir})", file=out)
    return verdict


def load_trace(path):
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):  # bare-array trace_event form
        return {"traceEvents": doc, "otherData": {}}
    return doc


def span_stats_from_events(events):
    """Aggregate ``X`` events by name -> duration lists (seconds)."""
    durs = {}
    for ev in events:
        if ev.get("ph") == "X":
            durs.setdefault(ev["name"], []).append(
                float(ev.get("dur", 0.0)) / 1e6
            )
    return durs


def span_stats_from_rollups(records):
    """Rebuild the breakdown rows from ``split="trace"`` rollup records
    (the no-trace.json fallback); values are already aggregated."""
    rows = {}
    for r in records:
        if r.get("split") == "trace" and r.get("event") == "span_rollup":
            rows[r["span"]] = {
                k: r[k] for k in (
                    "count", "total_ms", "mean_ms", "p50_ms", "p95_ms",
                    "p99_ms", "max_ms", "bytes_total", "gb_per_s",
                ) if k in r
            }
    return rows


def comm_stats_from_events(events):
    """Per ``comm.*`` span name: calls / wall / exact wire bytes (from
    the span args) plus the mean payload and world size the cost model
    needs to predict against."""
    out = {}
    for ev in events:
        if ev.get("ph") != "X" or not str(ev.get("name", "")).startswith(
            "comm."
        ):
            continue
        a = ev.get("args") or {}
        st = out.setdefault(ev["name"], {
            "count": 0, "total_ms": 0.0, "bytes_total": 0,
            "payload_total": 0, "world": a.get("world", 0),
        })
        st["count"] += 1
        st["total_ms"] += float(ev.get("dur", 0.0)) / 1e3
        st["bytes_total"] += int(a.get("wire_bytes", 0))
        st["payload_total"] += int(a.get("payload_bytes", 0))
    for st in out.values():
        st["mean_ms"] = st["total_ms"] / st["count"]
        st["payload_mean"] = st["payload_total"] // max(st["count"], 1)
        if st["total_ms"] > 0:
            st["gb_per_s"] = st["bytes_total"] / (
                st["total_ms"] / 1e3
            ) / 1e9
    return out


def comms_section(events, rows, other, costmodel_path, out):
    """Render the per-op comms table (+ model comparison + rank skew)."""
    stats = comm_stats_from_events(events)
    if not stats:  # JSONL-rollup fallback: bytes but no payload/world
        stats = {
            n: dict(r) for n, r in rows.items()
            if n.startswith("comm.") and r.get("bytes_total")
        }
    skew = (other or {}).get("comm_skew") or {}
    if not stats and not skew:
        return
    print("\n== Comms ==", file=out)
    model = None
    if costmodel_path:
        from pytorch_distributed_tpu.runtime import costmodel as cm

        # every comm span since r16 records which transport carried it;
        # refuse to compare measurements against a model fit on a
        # DIFFERENT transport (a tcp β is ~an order of magnitude off an
        # shm one — the meas/pred column would be confidently wrong).
        # Pre-r16 traces carry no transport arg: no check possible.
        kinds = sorted({
            str((ev.get("args") or {}).get("transport"))
            for ev in events
            if ev.get("ph") == "X"
            and str(ev.get("name", "")).startswith("comm.")
            and (ev.get("args") or {}).get("transport")
        })
        try:
            model = cm.CostModel.load(costmodel_path)
        except (OSError, ValueError, KeyError, TypeError) as e:
            # missing/unreadable stays graceful (reports render without
            # the pred column) ...
            print(f"  (costmodel {costmodel_path} unreadable: {e})",
                  file=out)
        # "hostring" (the facade-sweep label for the native shm ring)
        # and "shm" (the ring's own span kind) are the same physical
        # transport — normalize before comparing
        alias = {"hostring": "shm"}
        kinds = sorted({alias.get(k, k) for k in kinds})
        mkind = (alias.get(model.transport, model.transport)
                 if model is not None else None)
        if model is not None and kinds and mkind not in kinds:
            # ... but a transport MISMATCH raises: silence here is a
            # wrong number in the report
            raise cm.CostModelUnavailable(
                f"cost model {costmodel_path!r} was calibrated on "
                f"transport {model.transport!r} but this trace's comm "
                f"spans ran on {kinds} — refit per transport "
                f"(`collective_bench.py --transport ...`) or point "
                f"--costmodel at the matching fit"
            )
        if model is not None:
            print(f"  cost model: {costmodel_path} "
                  f"(transport={model.transport})", file=out)
    if stats:
        header = ("op", "calls", "total_ms", "mean_ms", "moved_MB",
                  "GB/s", "pred_ms", "meas/pred")
        widths = [max(24, *(len(n) for n in stats))] + [9] * 7
        print("  " + _fmt_row(header, widths), file=out)
        for name in sorted(
            stats, key=lambda n: -stats[n].get("total_ms", 0.0)
        ):
            st = stats[name]
            pred_ms = ratio = "-"
            if (model is not None and st.get("payload_mean")
                    and st.get("world")):
                try:
                    p = model.predict(
                        name[len("comm."):], st["payload_mean"],
                        int(st["world"]),
                    )
                    pred_ms = f"{p.seconds * 1e3:.3f}" + (
                        "*" if p.extrapolated else ""
                    )
                    if p.seconds > 0:
                        ratio = f"{st['mean_ms'] / 1e3 / p.seconds:.2f}"
                except KeyError:
                    pass
            print("  " + _fmt_row(
                (name, int(st.get("count", 0)),
                 f"{st.get('total_ms', 0.0):.1f}",
                 f"{st.get('mean_ms', 0.0):.3f}",
                 f"{st.get('bytes_total', 0) / 1e6:.1f}",
                 f"{st.get('gb_per_s', 0.0):.2f}",
                 pred_ms, ratio),
                widths,
            ), file=out)
        if model is not None:
            print("  (pred_ms from the α–β fit at each op's mean "
                  "payload; * = outside the calibrated range)", file=out)
    # per-transport wire accounting (r16): every armed comm span also
    # bumps a cumulative ``comm.bytes.<transport>`` counter per process.
    # Counters are per-GROUP-life cumulative and restart at 0 on a fresh
    # ring (elastic re-mesh), so sum per-(pid, counter) increments like
    # the comm.sync counters below.
    tbytes: dict = {}
    tprev: dict = {}
    for ev in events:
        if ev.get("ph") == "C" and str(ev.get("name", "")).startswith(
            "comm.bytes."
        ):
            name = ev["name"]
            v = float((ev.get("args") or {}).get("value", 0.0))
            k = (ev.get("pid"), name)
            p = tprev.get(k, 0.0)
            tbytes[name] = tbytes.get(name, 0.0) + (
                v - p if v >= p else v
            )
            tprev[k] = v
    if tbytes:
        cross = tbytes.get("comm.bytes.tcp", 0.0)
        parts = ", ".join(
            f"{n[len('comm.bytes.'):]} {v / 1e6:.2f} MB"
            for n, v in sorted(tbytes.items())
        )
        print(
            f"  Cross-host bytes: {cross / 1e6:.2f} MB over tcp "
            f"(per transport: {parts})", file=out,
        )
        stats["comm.bytes"] = {
            n[len("comm.bytes."):]: int(v) for n, v in tbytes.items()
        }
    # overlapped grad sync (r14): the engine's cumulative exposed/hidden
    # counters — how much of the comm wall the main thread actually
    # blocked on vs how much ran under concurrent work. Counters are
    # cumulative PER ENGINE LIFE and restart at 0 when the engine is
    # rebuilt (elastic re-mesh, reset_engine), so sum the per-(rank,
    # counter) increments: a drop below the previous value marks a
    # fresh engine whose reading counts in full.
    expose: dict = {}
    prev: dict = {}
    for ev in events:
        if ev.get("ph") == "C" and str(ev.get("name", "")).startswith(
            "comm.sync."
        ):
            name = ev["name"]
            v = float((ev.get("args") or {}).get("value", 0.0))
            k = (ev.get("pid"), name)
            p = prev.get(k, 0.0)
            expose[name] = expose.get(name, 0.0) + (
                v - p if v >= p else v
            )
            prev[k] = v
    if expose:
        exp = expose.get("comm.sync.exposed_s", 0.0)
        hid = expose.get("comm.sync.hidden_s", 0.0)
        total = exp + hid
        stats["comm.sync.overlap"] = {
            "exposed_s": exp, "hidden_s": hid,
            **({"exposed_ratio": exp / total} if total > 0 else {}),
        }
        print(
            f"  grad-sync overlap: comm exposed {exp:.3f}s / hidden "
            f"{hid:.3f}s"
            + (f" (exposed ratio {exp / total:.2f})" if total > 0
               else ""),
            file=out,
        )
    if skew:
        print("  per-rank straggler skew (merged trace):", file=out)
        for name, s in sorted(skew.items()):
            print(
                f"    {name:<24} x{s['occurrences']:<5} "
                f"mean={s['skew_ms_mean']:.3f}ms "
                f"p95={s['skew_ms_p95']:.3f}ms "
                f"max={s['skew_ms_max']:.3f}ms "
                f"({s['ranks']} ranks)", file=out,
            )
    return stats


#: spans that mean "one training step" — the unit the per-rank
#: straggler comparison is over (the trainer's and the elastic
#: engine's step sections respectively)
STEP_SPANS = ("train.step", "elastic.step")


def stragglers_section(events, records, out):
    """Per-rank step-time skew + the heterogeneity balancer's audit.

    Three inputs, each optional: merged-trace step spans (pid = rank
    after trace_merge, so per-rank step walls line up on one clock),
    the ``train.rank_skew`` counter the rebalancer emits (max/min
    per-microshard seconds across ranks as allgathered — the quantity
    assignments are derived from), and ``split="elastic"`` rebalance
    records (what the balancer actually did about it)."""
    per_rank = {}
    for ev in events:
        if ev.get("ph") == "X" and ev.get("name") in STEP_SPANS:
            per_rank.setdefault(ev.get("pid"), []).append(
                float(ev.get("dur", 0.0)) / 1e3
            )
    gauge = [
        float((ev.get("args") or {}).get("value", 0.0))
        for ev in events
        if ev.get("ph") == "C" and ev.get("name") == "train.rank_skew"
    ]
    rebalances = [
        r for r in records
        if r.get("split") == "elastic" and r.get("event") == "rebalance"
    ]
    if (len(per_rank) < 2) and not gauge and not rebalances:
        return None
    print("\n== Stragglers ==", file=out)
    summary = {}
    if len(per_rank) >= 2:  # skew needs a merged multi-rank trace
        means = {
            r: sum(d) / len(d) for r, d in per_rank.items() if d
        }
        skew = max(means.values()) / min(means.values())
        summary["step_skew"] = round(skew, 4)
        summary["ranks"] = len(means)
        print(
            f"  per-rank step time (merged trace, "
            f"{min(len(d) for d in per_rank.values())} steps/rank):",
            file=out,
        )
        for r in sorted(means):
            d = per_rank[r]
            print(
                f"    rank{r}: mean={means[r]:.2f}ms "
                f"p95={percentile(d, 95):.2f}ms max={max(d):.2f}ms",
                file=out,
            )
        print(
            f"  step-time skew (slowest/fastest rank): {skew:.2f}x",
            file=out,
        )
    if gauge:
        summary["rank_skew_gauge"] = gauge[-1]
        print(
            f"  train.rank_skew gauge: last {gauge[-1]:.2f}x, max "
            f"{max(gauge):.2f}x over {len(gauge)} rebalance "
            f"boundar{'y' if len(gauge) == 1 else 'ies'} (measured "
            f"per-microshard seconds, max/min across ranks)", file=out,
        )
    if rebalances:
        moved = sum(1 for r in rebalances if r.get("changed"))
        summary["rebalances"] = len(rebalances)
        summary["rebalances_changed"] = moved
        print(
            f"  rebalances: {len(rebalances)} boundar"
            f"{'y' if len(rebalances) == 1 else 'ies'}, ownership moved "
            f"at {moved}", file=out,
        )
        for r in rebalances:
            print(
                f"    step {r.get('step', '?'):>6}  "
                f"counts={r.get('counts')}  "
                f"skew={r.get('skew', 0.0):.2f}x  "
                f"({r.get('reason', '?')}"
                f"{', moved' if r.get('changed') else ', unchanged'})",
                file=out,
            )
    return summary


def pipeline_section(events, out):
    """Per-stage pipeline accounting (r20) from merged-trace
    ``pipeline.fwd``/``pipeline.bwd`` spans: busy vs window time, the
    idle (bubble) fraction, and the exposed-link share per stage.

    Whole-run numbers: step 0's compiles and the inter-step optimizer
    boundaries count as idle here, so these fractions read HIGH
    relative to the analytic ``(S-1)/(V*M+S-1)`` — the bench's
    steady-state-windowed measurement is the number the planner's
    pricing is checked against; this section is the triage view."""
    from pytorch_distributed_tpu.parallel.pipeline_schedule import (
        pipeline_trace_stats,
    )

    stats = pipeline_trace_stats(events)
    if not stats:
        return None
    print("\n== Pipeline ==", file=out)
    print(
        f"  {len(stats)} stage(s) with schedule spans (whole-run "
        f"window: compiles + step boundaries count as idle):", file=out,
    )
    for rank, s in stats.items():
        print(
            f"    stage{rank}: busy={s['busy_s']:.2f}s "
            f"window={s['window_s']:.2f}s bubble={s['bubble']:.3f} "
            f"link={s['link_s']:.2f}s "
            f"({s['link_s'] / s['window_s']:.3f} of window)", file=out,
        )
    worst = max(stats.values(), key=lambda s: s["bubble"])
    return {
        "stages": len(stats),
        "max_bubble": round(worst["bubble"], 4),
        "max_link_ratio": round(
            max(s["link_s"] / s["window_s"] for s in stats.values()), 4
        ),
    }


def fleet_section(records, out):
    """The serving-fleet picture (r18): per-engine telemetry + the
    router's migration/replay audit.

    Fires only on fleet-shaped runs — ``split="serve"`` records that
    carry the ``engine_id`` label (a lone engine omits it and keeps the
    single-engine Serving section below), or router ``migrate``/
    ``replay`` records. Per engine: request counts and TTFT
    percentiles from ``event="request"``, last slot occupancy from
    ``event="snapshot"``. Fleet-wide: KV migration totals (frames,
    wire bytes, payload bytes, pages) and evict-and-replay counts —
    the at-least-once cost of surviving an engine loss."""
    serve = [r for r in records if r.get("split") == "serve"]
    # migrate/replay also carry engine_id (the source/lost engine) —
    # only request/snapshot records describe an engine's own traffic
    labeled = [
        r for r in serve
        if r.get("engine_id")
        and r.get("event") in ("request", "snapshot")
    ]
    migrates = [r for r in serve if r.get("event") == "migrate"]
    replays = [r for r in serve if r.get("event") == "replay"]
    if not labeled and not migrates and not replays:
        return None
    print("\n== Fleet ==", file=out)
    summary = {}
    per_engine = {}
    for r in labeled:
        per_engine.setdefault(r["engine_id"], []).append(r)
    if per_engine:
        summary["engines"] = len(per_engine)
        print(f"  {len(per_engine)} engine(s) in the merged stream:",
              file=out)
        for eid in sorted(per_engine):
            recs = per_engine[eid]
            done = [
                r for r in recs
                if r.get("event") == "request"
                and r.get("status") == "completed"
            ]
            ttfts = [r["ttft_ms"] for r in done if "ttft_ms" in r]
            snaps = [r for r in recs if r.get("event") == "snapshot"]
            bits = [f"{len(done)} completed"]
            if ttfts:
                bits.append(
                    f"ttft p50={percentile(ttfts, 50):.1f}ms "
                    f"p99={percentile(ttfts, 99):.1f}ms"
                )
            if snaps:
                bits.append(
                    f"occupancy last "
                    f"{snaps[-1].get('slot_occupancy', 0.0):.2f}"
                )
            print(f"    {eid:<8} " + "  ".join(bits), file=out)
    if migrates:
        nbytes = sum(int(r.get("nbytes", 0)) for r in migrates)
        payload = sum(int(r.get("payload_nbytes", 0)) for r in migrates)
        pages = sum(int(r.get("n_pages", 0)) for r in migrates)
        summary["migrated_frames"] = len(migrates)
        summary["migrated_nbytes"] = nbytes
        summary["migrated_pages"] = pages
        print(
            f"  kv migration: {len(migrates)} frame(s), {pages} "
            f"page(s), {nbytes / 1e6:.2f}MB wire "
            f"({payload / 1e6:.2f}MB KV payload)", file=out,
        )
    if replays:
        lost = sorted({r.get("engine_id", "?") for r in replays})
        summary["replays"] = len(replays)
        summary["engines_lost"] = lost
        print(
            f"  replays: {len(replays)} request(s) re-admitted after "
            f"losing {', '.join(lost)} <-- at-least-once: lost decode "
            f"work is re-run, outputs stay deterministic", file=out,
        )
    return summary


def checkpoint_section(events, records, out):
    """The checkpoint audit trail + per-rank save cost (r17).

    Two inputs, each optional: ``split="ckpt"`` records the elastic
    engine writes (every save names its format/tag/world/replication
    and — sharded — this rank's bytes vs the world total; every restore
    names the tag it adopted, the world that WROTE it, and how hard the
    loader had to work: peer fetches, epochs walked back, stranded
    writes mopped up), and merged-trace ``elastic.checkpoint`` spans
    (pid = rank after trace_merge), which show whether save cost is
    balanced across ranks — the point of sharding it."""
    recs = [r for r in records if r.get("split") == "ckpt"]
    saves = [r for r in recs if r.get("event") == "save"]
    restores = [r for r in recs if r.get("event") == "restore"]
    per_rank = {}
    for ev in events:
        if ev.get("ph") == "X" and ev.get("name") == "elastic.checkpoint":
            per_rank.setdefault(ev.get("pid"), []).append(
                float(ev.get("dur", 0.0)) / 1e3
            )
    if not recs and len(per_rank) < 2:
        return None
    print("\n== Checkpoint ==", file=out)
    summary = {
        "saves": len(saves),
        "restores": len(restores),
        "peer_fetches": sum(
            int(r.get("peer_fetches", 0)) for r in restores
        ),
        "walked_back": sum(
            int(r.get("walked_back", 0)) for r in restores
        ),
    }
    if recs:
        sharded = sum(1 for r in saves if r.get("format") == "sharded")
        print(
            f"  saves: {len(saves)} ({sharded} sharded, "
            f"{len(saves) - sharded} full); restores: {len(restores)}",
            file=out,
        )
        for r in saves:
            if r.get("format") == "sharded":
                detail = (
                    f"world {r.get('world', '?')} repl "
                    f"{r.get('replication', '?')}  rank "
                    f"{r.get('rank_bytes', 0) / 1e6:.2f}MB / total "
                    f"{r.get('total_bytes', 0) / 1e6:.2f}MB"
                )
            else:
                detail = f"world {r.get('world', '?')} (gather to rank 0)"
            print(
                f"    step {r.get('step', '?'):>6}  save     "
                f"{r.get('format', '?'):<8} tag {r.get('tag', '?'):<12} "
                f"{detail}", file=out,
            )
        for r in restores:
            extras = []
            if r.get("peer_fetches"):
                extras.append(
                    f"peer_fetches {r['peer_fetches']} <-- sole-copy "
                    f"loss repaired from the replication peer"
                )
            if r.get("walked_back"):
                extras.append(
                    f"walked back {r['walked_back']} epoch(s) <-- "
                    f"INVESTIGATE (a whole checkpoint was unrestorable)"
                )
            if r.get("recovered"):
                extras.append(f"recovered {r['recovered']}")
            print(
                f"    step {r.get('step', '?'):>6}  restore  "
                f"tag {r.get('tag', '?'):<12} wrote by world "
                f"{r.get('ckpt_world', '?')} -> step "
                f"{r.get('restored_step', '?')}"
                + ("  " + "; ".join(extras) if extras else ""),
                file=out,
            )
    if len(per_rank) >= 2:
        totals = {r: sum(d) for r, d in per_rank.items()}
        balance = max(totals.values()) / max(min(totals.values()), 1e-9)
        summary["save_wall_skew"] = round(balance, 4)
        print(
            f"  per-rank save wall (merged trace, elastic.checkpoint):",
            file=out,
        )
        for r in sorted(per_rank):
            d = per_rank[r]
            print(
                f"    rank{r}: {len(d)} save(s), total "
                f"{totals[r]:.2f}ms, max {max(d):.2f}ms", file=out,
            )
        print(
            f"  save-wall skew (slowest/fastest rank): {balance:.2f}x "
            f"(sharded saves should be balanced; the full format "
            f"concentrates the write on rank 0)", file=out,
        )
    return summary


def _fmt_row(cols, widths):
    return "  ".join(str(c).rjust(w) for c, w in zip(cols, widths))


def phase_table(rows, wall_ms):
    header = ("span", "count", "total_ms", "mean_ms", "p50_ms",
              "p95_ms", "p99_ms", "max_ms", "%wall")
    widths = [max(28, *(len(n) for n in rows))] + [8] * 8 if rows else []
    if not rows:
        return ["  (no spans)"]
    out = [_fmt_row(header, widths)]
    for name in sorted(rows, key=lambda n: -rows[n].get("total_ms", 0.0)):
        r = rows[name]
        pct = (
            100.0 * r.get("total_ms", 0.0) / wall_ms if wall_ms else 0.0
        )
        out.append(_fmt_row(
            (name, int(r.get("count", 0)),
             f"{r.get('total_ms', 0.0):.1f}",
             f"{r.get('mean_ms', 0.0):.2f}",
             f"{r.get('p50_ms', 0.0):.2f}",
             f"{r.get('p95_ms', 0.0):.2f}",
             f"{r.get('p99_ms', 0.0):.2f}",
             f"{r.get('max_ms', 0.0):.2f}",
             f"{pct:.1f}"),
            widths,
        ))
    return out


def report(trace_path, metric_paths, top_n=10, out=None,
           costmodel_path=None, plan_path=None, flight_dir=None):
    # resolve the CURRENT sys.stdout, not import-time's: under pytest
    # capture an import-time default would pin the first importing
    # test's capture stream and every later caller would print into it
    out = out if out is not None else sys.stdout
    records = []
    for mp in metric_paths:
        try:
            records.extend(read_metrics(mp))
        except OSError as e:
            print(f"(metrics {mp} unreadable: {e})", file=out)

    events, other = [], {}
    if trace_path:
        try:
            doc = load_trace(trace_path)
            events = doc.get("traceEvents", [])
            other = doc.get("otherData", {}) or {}
        except (OSError, ValueError) as e:
            print(f"(trace {trace_path} unreadable: {e})", file=out)

    # -- step-phase breakdown ---------------------------------------------
    print("== Step-phase breakdown ==", file=out)
    if events:
        durs = span_stats_from_events(events)
        xs = [e for e in events if e.get("ph") == "X"]
        wall_ms = (
            (max(e["ts"] + e.get("dur", 0.0) for e in xs)
             - min(e["ts"] for e in xs)) / 1e3 if xs else 0.0
        )
        rows = {
            name: {
                "count": len(d),
                "total_ms": sum(d) * 1e3,
                "mean_ms": sum(d) / len(d) * 1e3,
                "p50_ms": percentile(d, 50) * 1e3,
                "p95_ms": percentile(d, 95) * 1e3,
                "p99_ms": percentile(d, 99) * 1e3,
                "max_ms": max(d) * 1e3,
            }
            for name, d in durs.items()
        }
        src = f"trace: {trace_path}, wall {wall_ms / 1e3:.2f}s"
    else:
        rows = span_stats_from_rollups(records)
        wall_ms = sum(r.get("total_ms", 0.0) for r in rows.values())
        src = "JSONL span rollups (no trace.json; %wall = share of traced time)"
    print(f"  source: {src}", file=out)
    for line in phase_table(rows, wall_ms):
        print("  " + line, file=out)

    # -- widest spans ------------------------------------------------------
    if events:
        print(f"\n== Top {top_n} widest spans ==", file=out)
        widest = sorted(
            (e for e in events if e.get("ph") == "X"),
            key=lambda e: -e.get("dur", 0.0),
        )[:top_n]
        for e in widest:
            args_note = f"  args={e['args']}" if e.get("args") else ""
            print(
                f"  {e.get('dur', 0.0) / 1e3:10.2f} ms  {e['name']:<28}"
                f" @ t={e['ts'] / 1e6:.3f}s tid={e.get('tid')}{args_note}",
                file=out,
            )

    # -- recompile sentinel ------------------------------------------------
    print("\n== Recompiles (after warm-up) ==", file=out)
    # one event="recompiles" record per attempt (each fit() has a fresh
    # tracer), so SUM across records; the trace.json duplicates the last
    # surviving attempt's counts, so merge it by max, not by adding
    jsonl_rec = {}
    for r in records:
        if r.get("split") == "trace" and r.get("event") == "recompiles":
            for k, v in r.items():
                if k.startswith("recompiles."):
                    name = k[len("recompiles."):]
                    jsonl_rec[name] = jsonl_rec.get(name, 0) + int(v)
    recompiles = dict(other.get("recompiles") or {})
    for name, n in jsonl_rec.items():
        recompiles[name] = max(recompiles.get(name, 0), n)
    if recompiles:
        for name, n in sorted(recompiles.items()):
            print(f"  {name}: {n} steady-state recompile(s)  <-- "
                  f"INVESTIGATE (silent 100x regression shape)", file=out)
    else:
        print("  none — every jitted callable compiled once", file=out)

    # -- comms -------------------------------------------------------------
    comms = comms_section(events, rows, other, costmodel_path, out)

    # -- stragglers (r15: heterogeneity picture) ---------------------------
    stragglers = stragglers_section(events, records, out)

    # -- pipeline stages (r20: per-stage busy/bubble/link picture) ---------
    pipe = pipeline_section(events, out)

    # -- checkpoint audit (r17: sharded save/restore trail) ----------------
    ckpt = checkpoint_section(events, records, out)

    # -- serving fleet (r18: per-engine telemetry + migration audit) -------
    fleet = fleet_section(records, out)

    # -- auto-parallel plan ------------------------------------------------
    plan_doc = plan_section(plan_path, out)

    # -- goodput -----------------------------------------------------------
    print("\n== Goodput ==", file=out)
    g = summarize_goodput(records)
    if g["attempts_recorded"]:
        print(
            f"  goodput {g['goodput_pct']:.1f}% over "
            f"{g['wall_s']:.1f}s wall ({g['attempts_recorded']} "
            f"attempt(s) recorded)", file=out,
        )
        for k in sorted(k for k in g if k.endswith("_s") and k != "wall_s"):
            print(f"    {k:<16} {g[k]:10.2f}", file=out)
    else:
        print("  no goodput records in the metrics stream", file=out)
    # elastic-world membership transitions ride the same stream
    # (train/elastic_world.py, split="elastic"): each in-process resize
    # names its epochs, the surviving world size, and what it cost —
    # the goodput 'resize' bucket, itemized
    views = [
        r for r in records
        if r.get("split") == "elastic" and r.get("event") == "view_change"
    ]
    if views:
        total_resize = sum(float(r.get("resize_s", 0.0)) for r in views)
        print(
            f"  membership: {len(views)} view change(s), "
            f"{total_resize:.2f}s total resize cost", file=out,
        )
        for r in views:
            print(
                f"    step {r.get('step', '?'):>6}  epoch "
                f"{r.get('from_epoch', '?')} -> {r.get('epoch', '?')}  "
                f"world {r.get('world_size', '?')}  "
                f"({r.get('reason', '?')}, {r.get('resize_s', 0.0):.2f}s)",
                file=out,
            )
        g["view_changes"] = len(views)
        g["resize_total_s"] = round(total_resize, 4)

    # -- serve telemetry, if present --------------------------------------
    serve_recs = [r for r in records if r.get("split") == "serve"]
    ttfts = [r["ttft_ms"] for r in serve_recs if "ttft_ms" in r]
    snaps = [r for r in serve_recs if r.get("event") == "snapshot"]
    serve = {}
    if ttfts or snaps:
        print("\n== Serving ==", file=out)
    if ttfts:
        serve["ttft_n"] = len(ttfts)
        print(
            f"  TTFT n={len(ttfts)} p50={percentile(ttfts, 50):.1f}ms "
            f"p95={percentile(ttfts, 95):.1f}ms "
            f"p99={percentile(ttfts, 99):.1f}ms", file=out,
        )
    if snaps:
        # the paged-pool / speculation gauges ride the same snapshot
        # records (serve/telemetry.py): report the saturation picture —
        # peak across snapshots for occupancy, latest for cumulative
        # counters
        last = snaps[-1]
        peak_slots = max(s.get("slots_occupied", 0) for s in snaps)
        serve["snapshots"] = len(snaps)
        print(
            f"  slots: peak {peak_slots}/{last.get('slots_total', '?')} "
            f"occupied over {len(snaps)} snapshots, "
            f"{last.get('decode_ticks', 0)} decode ticks", file=out,
        )
        if "pages_in_use" in last:
            peak_pages = max(s.get("pages_in_use", 0) for s in snaps)
            serve["peak_pages"] = peak_pages
            print(
                f"  kv pool: peak {peak_pages}/"
                f"{last.get('pages_total', '?')} pages in use "
                f"({100.0 * peak_pages / max(last.get('pages_total', 1), 1):.0f}"
                f"% of pool), prefix hit rate "
                f"{last.get('prefix_hit_rate', 0.0):.3f} "
                f"(fraction of prompt tokens served from shared pages)",
                file=out,
            )
        if "decode_hbm_bytes_per_token" in last:
            bpt = last["decode_hbm_bytes_per_token"]
            serve["decode_hbm_bytes_per_token"] = bpt
            gather = last.get("decode_gather_bytes", 0)
            print(
                f"  decode HBM: {bpt:,.0f} analytic bytes/token, "
                f"{gather / 1e6:,.1f} MB total gather traffic "
                f"(the dense-intermediate tax — 0 under the paged "
                f"kernel, bucket-wide under the gather fallback, "
                f"max_len-wide in dense mode)", file=out,
            )
        if last.get("spec_verifies"):
            apv = last.get("spec_accepted", 0) / last["spec_verifies"]
            serve["spec_accepted_per_verify"] = apv
            print(
                f"  speculation: {last['spec_verifies']} verifies, "
                f"{last.get('spec_accepted', 0)}/"
                f"{last.get('spec_drafted', 0)} drafts accepted "
                f"({apv:.2f} accepted tokens/verify; each verify also "
                f"emits its correction token)", file=out,
            )
    # -- hang autopsy, if the run left flight dumps -----------------------
    hang = hang_section(flight_dir, out)

    return {"spans": rows, "recompiles": recompiles, "goodput": g,
            "comms": comms or {}, "stragglers": stragglers or {},
            "pipeline": pipe or {}, "checkpoint": ckpt or {},
            "fleet": fleet or {}, "plan": plan_doc, "serve": serve,
            "hang": hang}


def main(argv=None):
    args = parse_args(argv)
    if not args.run_dir and not args.trace and not args.metrics:
        print("nothing to report: pass RUN_DIR or --trace/--metrics",
              file=sys.stderr)
        return 2
    (trace_path, metric_paths, costmodel_path, plan_path,
     flight_dir) = _discover(args)
    if (not trace_path and not metric_paths and not plan_path
            and not flight_dir):
        print(
            f"no trace.json, *.jsonl, plan.json or flight-rank*.json "
            f"found under {args.run_dir!r}", file=sys.stderr,
        )
        return 2
    report(trace_path, metric_paths, top_n=args.top,
           costmodel_path=costmodel_path, plan_path=plan_path,
           flight_dir=flight_dir)
    return 0


if __name__ == "__main__":
    sys.exit(main())
