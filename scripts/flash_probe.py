"""Bounded Pallas flash-attention probe for the real chip.

VERDICT r1 #2: the flash kernels (ops/flash_attention.py) have never
executed on actual TPU hardware — interpret-mode tests only — and one r2
attempt saw the fwd kernel's remote compile exceed 9 minutes. This probe
walks shapes smallest-first with wall-clock logging and the persistent
compilation cache enabled, so each shape's verdict (compile time, run
time, numerics vs the XLA path) is recorded even if a later shape wedges.

Run ON THE CHIP ONLY (it dials the relay):  python scripts/flash_probe.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

t0 = time.time()


def log(msg):
    print(f"[{time.time() - t0:8.1f}s] {msg}", flush=True)


import jax
import jax.numpy as jnp
import numpy as np

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.ops.attention import dot_product_attention
from pytorch_distributed_tpu.ops.flash_attention import flash_attention

SHAPES = [  # (B, S, H, D) smallest-first
    (1, 256, 4, 64),
    (2, 512, 8, 64),
    (4, 1024, 8, 64),
    (8, 1024, 16, 64),  # the GPT-2-medium bench shape that wedged in r2
]


def main():
    global t0
    from pytorch_distributed_tpu.utils.benchlock import start_measurement

    # lock BEFORE the budget clock starts: queue time behind another
    # run is not this run's measurement time
    _lock, t0 = start_measurement()  # noqa: F841 — held for life
    ptd.enable_compilation_cache()
    log(f"platform={ptd.platform()} kind={jax.devices()[0].device_kind}")
    for shape in SHAPES:
        B, S, H, D = shape
        rng = np.random.default_rng(0)
        q, k, v = (
            jnp.asarray(rng.normal(size=(B, S, H, D)).astype(np.float32))
            .astype(jnp.bfloat16)
            for _ in range(3)
        )
        log(f"--- {shape} fwd compile start")
        f = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
        t = time.time()
        out = f(q, k, v)
        got = np.asarray(out.astype(jnp.float32))
        log(f"{shape} fwd compile+run {time.time() - t:.1f}s")
        want = np.asarray(
            dot_product_attention(q, k, v, causal=True).astype(jnp.float32)
        )
        err = np.max(np.abs(got - want))
        log(f"{shape} fwd max|err| vs xla = {err:.4f}")

        log(f"{shape} bwd compile start")
        g = jax.jit(
            jax.grad(
                lambda q, k, v: flash_attention(q, k, v, causal=True)
                .astype(jnp.float32)
                .sum(),
                argnums=(0, 1, 2),
            )
        )
        t = time.time()
        dq, dk, dv = g(q, k, v)
        jax.block_until_ready(dq)
        float(dq.astype(jnp.float32).ravel()[0])
        log(f"{shape} bwd compile+run {time.time() - t:.1f}s")

        # steady-state timing
        iters = 20
        t = time.time()
        for _ in range(iters):
            out = f(q, k, v)
        float(out.astype(jnp.float32).ravel()[0])
        dt = (time.time() - t) / iters
        flops = 4 * B * H * S * S * D / 2  # causal: half the square
        log(f"{shape} fwd {dt * 1e3:.2f}ms  ~{flops / dt / 1e12:.1f} TFLOP/s")
    log("ALL SHAPES OK")


if __name__ == "__main__":
    main()
