"""GPT-2-medium train-step variant timing on the real chip.

Decides bench.py's transformer configuration from measurements, not
guesses: times the train step across remat policies x {xla, flash}
attention x {full, chunked} loss at the bench shape (batch 8, seq 1024).
Every variant runs remat=True: without remat the scanned 24-layer
backward saves [L,B,S,S] attention activations — 37 GB against v5e's
15.75 GB HBM (measured OOM, r3 bench). Flash attention runs LAST (its
remote compile is the documented relay-wedge hazard). Run ON THE CHIP
ONLY, never under an external kill timer (BASELINE.md relay-wedge rule);
budgets its own wall clock via PTD_PROBE_BUDGET_S (default 1500s).
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

t0 = time.time()
BUDGET_S = float(os.environ.get("PTD_PROBE_BUDGET_S", "1500"))


def log(msg):
    print(f"[{time.time() - t0:8.1f}s] {msg}", flush=True)


import jax
import jax.numpy as jnp
import numpy as np
import optax

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from pytorch_distributed_tpu.ops.attention import set_attention_impl
from pytorch_distributed_tpu.parallel import DataParallel
from pytorch_distributed_tpu.train import (
    TrainState,
    build_train_step,
    causal_lm_loss_fn,
)

BATCH, SEQ = 8, 1024
WARMUP, ITERS = 3, 20


def time_variant(attn: str, vocab_chunk, model, params, batch):
    set_attention_impl(attn)
    try:
        # private param copy: the step donates its state, and at world=1
        # place() is placement-only — sharing the init tree across
        # variants would feed variant 2 already-deleted arrays
        params = jax.tree_util.tree_map(jnp.array, params)
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.adamw(3e-4)
        )
        strategy = DataParallel()
        state = strategy.place(state)
        step = strategy.compile(
            build_train_step(
                causal_lm_loss_fn(model, vocab_chunk_size=vocab_chunk)
            ),
            state,
        )
        t = time.time()
        for _ in range(WARMUP):
            state, metrics = step(state, batch)
        loss = float(metrics["loss"])
        compile_s = time.time() - t
        t = time.time()
        for _ in range(ITERS):
            state, metrics = step(state, batch)
        loss = float(metrics["loss"])
        dt = (time.time() - t) / ITERS
        tok = BATCH * SEQ / dt
        log(
            f"attn={attn:5s} chunk={str(vocab_chunk):5s} "
            f"{dt * 1e3:7.1f}ms/step {tok:9.0f} tok/s loss={loss:.3f} "
            f"(compile+warmup {compile_s:.0f}s)"
        )
        del state, step
    finally:
        set_attention_impl("auto")


def main():
    global t0
    from pytorch_distributed_tpu.utils.benchlock import start_measurement

    # lock BEFORE the budget clock starts: queue time behind another
    # run is not this run's measurement time
    _lock, t0 = start_measurement()  # noqa: F841 — held for life
    ptd.enable_compilation_cache()
    ptd.init_process_group()
    log(f"platform={ptd.platform()} kind={jax.devices()[0].device_kind}")
    import dataclasses

    cfg = GPT2Config.medium()
    params = GPT2LMHead(cfg).init(
        jax.random.key(0), jnp.zeros((1, SEQ), jnp.int32)
    )["params"]

    def mkmodel(policy):
        # remat changes no parameters — one init serves every variant
        return GPT2LMHead(dataclasses.replace(
            cfg, remat=True, remat_policy=policy
        ))
    strategy = DataParallel()
    rng = np.random.default_rng(0)
    batch = strategy.shard_batch(
        {
            "input_ids": rng.integers(
                cfg.vocab_size, size=(BATCH, SEQ)
            ).astype(np.int32)
        }
    )
    variants = [
        ("full", "xla", None),
        ("dots_no_batch", "xla", None),
        ("full", "xla", 8192),
        ("full", "flash", None),  # LAST: compile hazard
    ]
    for policy, attn, chunk in variants:
        if time.time() - t0 > BUDGET_S:
            log(f"budget {BUDGET_S:.0f}s spent — skipping remaining")
            break
        try:
            log(f"variant remat={policy} attn={attn} chunk={chunk} ...")
            time_variant(attn, chunk, mkmodel(policy), params, batch)
        except Exception as e:
            log(f"remat={policy} attn={attn} chunk={chunk} FAILED: "
                f"{type(e).__name__}: {e}")
    log("DONE")


if __name__ == "__main__":
    main()
